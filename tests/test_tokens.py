"""Tests for the chained block-hash token layer (mirrors reference tokens.rs tests)."""
import random

from dynamo_tpu.tokens import (
    NO_PARENT,
    TokenBlockSequence,
    compute_block_hashes,
    hash_tokens,
    salt_hash,
)


def test_hash_determinism_and_chaining():
    a = hash_tokens([1, 2, 3, 4])
    assert a == hash_tokens([1, 2, 3, 4])
    assert a != hash_tokens([1, 2, 3, 5])
    # chaining: same tokens, different parent -> different hash
    assert hash_tokens([1, 2, 3, 4], parent=a) != a


def test_compute_block_hashes_ignores_partial_tail():
    toks = list(range(10))
    h4 = compute_block_hashes(toks, block_size=4)
    assert len(h4) == 2  # 10 tokens -> 2 complete blocks of 4, tail of 2 dropped
    # prefix property: first block hash equal across longer sequences
    h4b = compute_block_hashes(list(range(12)), block_size=4)
    assert h4b[:2] == h4
    assert len(h4b) == 3


def test_sequence_incremental_matches_batch():
    random.seed(0)
    toks = [random.randrange(32000) for _ in range(133)]
    seq = TokenBlockSequence(block_size=16)
    completed = seq.extend(toks)
    assert [b.block_hash for b in completed] == seq.block_hashes()
    assert seq.block_hashes() == compute_block_hashes(toks, 16)
    assert seq.total_tokens == 133
    assert len(seq.partial) == 133 % 16
    assert seq.tokens == toks


def test_salt_separates_models():
    toks = list(range(32))
    assert compute_block_hashes(toks, 16, salt="model-a") != compute_block_hashes(
        toks, 16, salt="model-b"
    )
    assert salt_hash("") == NO_PARENT


def test_truncate():
    toks = list(range(100))
    seq = TokenBlockSequence.from_tokens(toks, 16)
    seq.truncate(40)
    assert seq.total_tokens == 40
    assert seq.tokens == toks[:40]
    assert seq.block_hashes() == compute_block_hashes(toks[:40], 16)
    # re-extending reproduces the original chain
    seq.extend(toks[40:])
    assert seq.block_hashes() == compute_block_hashes(toks, 16)


def test_append_returns_block_on_boundary():
    seq = TokenBlockSequence(block_size=4)
    assert seq.append(1) is None
    assert seq.append(2) is None
    assert seq.append(3) is None
    blk = seq.append(4)
    assert blk is not None and blk.position == 0 and blk.parent_hash == NO_PARENT
