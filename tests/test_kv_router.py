"""KV router tests (reference kv_router/: indexer.rs, scheduler.rs,
sequence.rs tests).

The keystone behavior test runs the router over N mocker workers and checks
that prefix-heavy traffic concentrates on the warm worker — the reference's
headline 3x-TTFT feature (BASELINE.md), exercised on CPU.
"""
import asyncio
import random

from dynamo_tpu.kv_router.indexer import ApproxKvIndexer, KvIndexer
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent,
    KvEventKind,
    StoredBlock,
)
from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
    SchedulingRequest,
    softmax_sample,
)
from dynamo_tpu.kv_router.sequence import ActiveSequences
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.tokens import TokenBlockSequence, compute_block_hashes

BS = 4  # block size


def stored(worker, hashes, parent=0):
    return KvCacheEvent(
        kind=KvEventKind.STORED,
        worker_id=worker,
        parent_hash=parent,
        blocks=[StoredBlock(block_hash=h) for h in hashes],
    )


# ---------------------------------------------------------------------------
# indexer


def test_indexer_overlap_walk():
    idx = KvIndexer(BS)
    toks = list(range(1, 17))  # 4 blocks
    hashes = compute_block_hashes(toks, BS)
    idx.apply_event(stored("w0", hashes[:3]))
    idx.apply_event(stored("w1", hashes[:1]))
    s = idx.find_matches(hashes)
    assert s.scores == {"w0": 3, "w1": 1}
    # removal shortens the walk for that worker only
    idx.apply_event(
        KvCacheEvent(
            kind=KvEventKind.REMOVED, worker_id="w0",
            removed_hashes=[hashes[2]],
        )
    )
    s = idx.find_matches(hashes)
    assert s.scores == {"w0": 2, "w1": 1}


def test_indexer_walk_stops_at_first_gap():
    idx = KvIndexer(BS)
    toks = list(range(1, 17))
    hashes = compute_block_hashes(toks, BS)
    idx.apply_event(stored("w0", [hashes[0], hashes[2]]))  # gap at 1
    s = idx.find_matches(hashes)
    assert s.scores == {"w0": 1}  # walk stops at hashes[1]


def test_indexer_worker_removal_and_clear():
    idx = KvIndexer(BS)
    hashes = compute_block_hashes(list(range(1, 9)), BS)
    idx.apply_event(stored("w0", hashes))
    idx.apply_event(stored("w1", hashes))
    idx.remove_worker("w0")
    assert idx.find_matches(hashes).scores == {"w1": 2}
    idx.apply_event(KvCacheEvent(kind=KvEventKind.CLEARED, worker_id="w1"))
    assert idx.find_matches(hashes).scores == {}


def test_approx_indexer_records_routing_decisions():
    idx = ApproxKvIndexer(BS, ttl_s=60.0)
    hashes = compute_block_hashes(list(range(1, 13)), BS)
    assert idx.find_matches(hashes).scores == {}
    idx.process_routing_decision("w2", hashes)
    assert idx.find_matches(hashes).scores == {"w2": 3}


# ---------------------------------------------------------------------------
# scheduler


def test_softmax_sample_temperature_zero_is_argmin():
    rng = random.Random(0)
    logits = {"a": 5.0, "b": 1.0, "c": 9.0}
    for _ in range(20):
        assert softmax_sample(logits, 0.0, rng) == "b"


def test_selector_prefers_overlap_and_low_load():
    sel = DefaultWorkerSelector(
        KvRouterConfig(overlap_score_weight=1.0, router_temperature=0.0)
    )
    from dynamo_tpu.kv_router.indexer import OverlapScores

    req = SchedulingRequest(
        isl_tokens=BS * 4,
        overlap=OverlapScores(scores={"warm": 3}),
        potential_blocks={"warm": 10, "cold": 10},
    )
    w, overlap = sel.select_worker(["warm", "cold"], req, BS)
    assert w == "warm" and overlap == 3
    # heavy load on the warm worker flips the decision
    req2 = SchedulingRequest(
        isl_tokens=BS * 4,
        overlap=OverlapScores(scores={"warm": 3}),
        potential_blocks={"warm": 50, "cold": 10},
    )
    w2, _ = sel.select_worker(["warm", "cold"], req2, BS)
    assert w2 == "cold"


# ---------------------------------------------------------------------------
# active sequences


def test_active_sequences_shared_blocks_and_partial():
    a = ActiveSequences(BS)
    seq1 = TokenBlockSequence.from_tokens(list(range(1, 10)), BS)  # 2 full + tail
    a.add_request("r1", seq1)
    assert a.active_blocks == 3  # 2 shared full + 1 partial
    seq2 = TokenBlockSequence.from_tokens(list(range(1, 10)), BS)
    assert a.new_blocks(seq2) == 1  # only its own partial is new
    a.add_request("r2", seq2)
    assert a.active_blocks == 4
    a.free("r1")
    assert a.active_blocks == 3
    a.free("r2")
    assert a.active_blocks == 0


def test_active_sequences_push_promotes_blocks():
    a = ActiveSequences(BS)
    seq = TokenBlockSequence.from_tokens([1, 2, 3], BS)
    a.add_request("r", seq)
    assert a.active_blocks == 1  # partial only
    a.push("r", 4)  # seals block 1
    assert a.active_blocks == 1  # full block, no partial
    a.push("r", 5)
    assert a.active_blocks == 2  # full + new partial


# ---------------------------------------------------------------------------
# end-to-end routing over mocker workers


async def test_router_concentrates_prefix_traffic():
    """Same-prefix requests should converge on the warm worker; the
    indexer feeds on the workers' real KV events."""
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    engines = {}
    for i in range(3):
        wid = f"w{i}"
        eng = MockerEngine(
            MockerArgs(
                speedup_ratio=100.0, page_size=BS, num_pages=64,
                worker_id=wid,
            ),
            on_kv_event=router.indexer.apply_event,
        )
        engines[wid] = eng
        push.add_worker(wid, eng)

    shared_prefix = list(range(1, 33))  # 8 blocks

    async def one(i):
        req = PreprocessedRequest(
            token_ids=shared_prefix + [100 + i],
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
        )
        toks = []
        async for out in push.generate(req):
            toks.extend(out.token_ids)
        return toks

    # first request warms one worker
    await one(0)
    counts = {w: 0 for w in engines}
    for i in range(1, 10):
        before = {w: e.tokens_generated for w, e in engines.items()}
        await one(i)
        for w, e in engines.items():
            if e.tokens_generated > before[w]:
                counts[w] += 1
    # all follow-ups should land on the warmed worker (temperature 0)
    assert max(counts.values()) == 9, counts
    assert sorted(counts.values()) == [0, 0, 9]
    for e in engines.values():
        await e.stop()


async def test_router_tracks_and_frees_active_blocks():
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    eng = MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=BS))
    push.add_worker("w0", eng)
    req = PreprocessedRequest(
        token_ids=list(range(1, 14)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
    )
    toks = []
    async for out in push.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 6
    # after completion the request's blocks are freed
    assert router.sequences.active_blocks() == {"w0": 0}
    await eng.stop()


async def test_router_evicts_dead_worker_and_reroutes():
    """Advisor r2 (high): a warm prefix mapped to a dead worker must not
    deterministically 500 for the whole lease window — on a connection
    error the router evicts the worker (indexer included) and re-routes."""
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)

    class DeadEngine:
        async def generate(self, request):
            raise ConnectionError("connection refused")
            yield  # pragma: no cover — make it an async generator

    live = MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=BS,
                                   num_pages=64, worker_id="live"))
    push.add_worker("dead", DeadEngine())
    push.add_worker("live", live)

    # warm ONLY the dead worker in the indexer: temp-0 routing will always
    # prefer it for this prefix
    prefix = list(range(1, 33))
    hashes = compute_block_hashes(prefix, BS)
    router.indexer.apply_event(stored("dead", hashes))

    req = PreprocessedRequest(
        token_ids=prefix + [99],
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
    )
    toks = []
    async for out in push.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 4                  # served by the live worker
    assert "dead" not in push.workers      # evicted
    assert router.indexer.find_matches(hashes).scores.get("dead") is None
    # subsequent requests route straight to the live worker
    toks2 = []
    async for out in push.generate(req):
        toks2.extend(out.token_ids)
    assert len(toks2) == 4
    await live.stop()


async def test_router_raises_when_all_workers_dead():
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)

    class DeadEngine:
        async def generate(self, request):
            raise ConnectionError("refused")
            yield  # pragma: no cover

    push.add_worker("d0", DeadEngine())
    push.add_worker("d1", DeadEngine())
    req = PreprocessedRequest(
        token_ids=list(range(1, 10)),
        stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
    )
    try:
        async for _ in push.generate(req):
            pass
        raise AssertionError("expected ConnectionError")
    except ConnectionError:
        pass


def test_kv_event_resync_heals_dropped_and_stale_state():
    """VERDICT r2 weak #8: the pub/sub plane is lossy; the allocator's
    periodic snapshot resync (CLEARED + full STORED set) converges an
    indexer that missed events in EITHER direction."""
    from dynamo_tpu.engine.cache import PageAllocator
    from dynamo_tpu.tokens import compute_block_hashes

    ps = 4
    alloc = PageAllocator(num_pages=16, page_size=ps, worker_id="w0")
    hashes = compute_block_hashes(list(range(1, 13)), ps)  # 3 blocks
    pages = alloc.allocate(3)
    parent = 0
    for pg, h in zip(pages, hashes):
        alloc.commit(pg, h, parent)
        parent = h

    idx = KvIndexer(ps)
    # the indexer saw only 2 of the 3 STOREDs (one dropped) plus a STORED
    # for a block the worker has since evicted (stale REMOVED dropped)
    idx.apply_event(stored("w0", hashes[:2]))
    idx.apply_event(stored("w0", [999_999]))
    assert idx.find_matches(hashes).scores == {"w0": 2}

    for ev in alloc.snapshot_stored_events():
        ev.worker_id = "w0"  # the publisher sink stamps this in production
        idx.apply_event(ev)
    # converged: all 3 real blocks present, the stale one gone
    assert idx.find_matches(hashes).scores == {"w0": 3}
    assert idx.find_matches([999_999]).scores == {}
