"""Flash decode kernel parity: the Pallas TPU kernel (interpret mode on
CPU) vs the pure-jnp reference, across context lengths, chunking, ring
occupancy, and the scratch-lane layout (reference analogue: vLLM's
paged-attention kernel tests; ours covers the round-4 two-tier
ctx+ring design, ops/flash_decode.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.flash_decode import (
    flash_decode_attention,
    flash_decode_attention_reference,
)

L, NKV, NH, HD = 3, 2, 4, 16
B, S, R = 4, 64, 4


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    ck = jnp.asarray(rng.randn(L, NKV, B + 1, S, HD) * 0.3, jnp.float32)
    cv = jnp.asarray(rng.randn(L, NKV, B + 1, S, HD) * 0.3, jnp.float32)
    rk = jnp.asarray(rng.randn(L, NKV, B, R, HD) * 0.3, jnp.float32)
    rv = jnp.asarray(rng.randn(L, NKV, B, R, HD) * 0.3, jnp.float32)
    q = jnp.asarray(rng.randn(B, NH, HD), jnp.float32)
    return q, ck, cv, rk, rv


def both(data, ctx, base, chunk, layer=0):
    q, ck, cv, rk, rv = data
    got = flash_decode_attention(
        q, ck, cv, rk, rv, jnp.int32(layer), ctx, base,
        chunk=chunk, interpret=True,
    )
    want = flash_decode_attention_reference(
        q, ck, cv, rk, rv, jnp.int32(layer), ctx, base
    )
    return np.asarray(got), np.asarray(want)


@pytest.mark.parametrize("chunk", [16, 32, 64])
def test_kernel_matches_reference(data, chunk):
    # mid-round state: ring holds 2 tokens beyond each slot's ctx base
    base = jnp.asarray([1, 15, 31, 60], jnp.int32)
    ctx = base + 2
    for layer in (0, L - 1):
        got, want = both(data, ctx, base, chunk, layer)
        # interpret mode emulates the MXU's bf16 passes -> ~1e-3 tolerance
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_ring_only_context(data):
    """Fresh slots: base=0, everything lives in the ring."""
    base = jnp.asarray([0, 0, 0, 0], jnp.int32)
    ctx = jnp.asarray([1, 2, 3, 4], jnp.int32)
    got, want = both(data, ctx, base, 16)
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_single_token_context_is_v_row(data):
    """base=0, ctx=1: softmax over one ring position — output must be
    (approximately, interpret-mode bf16 dots) the ring v row 0."""
    q, ck, cv, rk, rv = data
    base = jnp.zeros(B, jnp.int32)
    ctx = jnp.ones(B, jnp.int32)
    got = flash_decode_attention(
        q, ck, cv, rk, rv, jnp.int32(1), ctx, base,
        chunk=32, interpret=True,
    )
    for b in range(B):
        for n in range(NH):
            h = n // (NH // NKV)
            np.testing.assert_allclose(
                np.asarray(got)[b, n], np.asarray(rv)[1, h, b, 0],
                rtol=5e-3, atol=5e-3,
            )


def test_chunk_boundary_contexts(data):
    """Ring bases straddling chunk boundaries agree with the reference
    (the per-slot DMA-skip index math)."""
    for bases in ([15, 16, 17, 31], [32, 33, 48, 60]):
        base = jnp.asarray(bases, jnp.int32)
        ctx = base + 3
        got, want = both(data, ctx, base, 16)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)
