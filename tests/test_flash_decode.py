"""Flash decode kernel parity: the Pallas TPU kernel (interpret mode on
CPU) vs the pure-jnp reference, across context lengths, chunking, ring
occupancy, and the scratch-lane layout (reference analogue: vLLM's
paged-attention kernel tests; ours covers the round-4 two-tier
ctx+ring design, ops/flash_decode.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.flash_decode import (
    _pick_chunk,
    flash_decode_attention,
    flash_decode_attention_reference,
)

L, NKV, NH, HD = 3, 2, 4, 16
B, S, R = 4, 64, 4


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    ck = jnp.asarray(rng.randn(L, NKV, B + 1, S, HD) * 0.3, jnp.float32)
    cv = jnp.asarray(rng.randn(L, NKV, B + 1, S, HD) * 0.3, jnp.float32)
    rk = jnp.asarray(rng.randn(L, NKV, B, R, HD) * 0.3, jnp.float32)
    rv = jnp.asarray(rng.randn(L, NKV, B, R, HD) * 0.3, jnp.float32)
    q = jnp.asarray(rng.randn(B, NH, HD), jnp.float32)
    return q, ck, cv, rk, rv


def both(data, ctx, base, chunk, layer=0):
    q, ck, cv, rk, rv = data
    got = flash_decode_attention(
        q, ck, cv, rk, rv, jnp.int32(layer), ctx, base,
        chunk=chunk, interpret=True,
    )
    want = flash_decode_attention_reference(
        q, ck, cv, rk, rv, jnp.int32(layer), ctx, base
    )
    return np.asarray(got), np.asarray(want)


@pytest.mark.parametrize("chunk", [16, 32, 64])
def test_kernel_matches_reference(data, chunk):
    # mid-round state: ring holds 2 tokens beyond each slot's ctx base
    base = jnp.asarray([1, 15, 31, 60], jnp.int32)
    ctx = base + 2
    for layer in (0, L - 1):
        got, want = both(data, ctx, base, chunk, layer)
        # interpret mode emulates the MXU's bf16 passes -> ~1e-3 tolerance
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_ring_only_context(data):
    """Fresh slots: base=0, everything lives in the ring."""
    base = jnp.asarray([0, 0, 0, 0], jnp.int32)
    ctx = jnp.asarray([1, 2, 3, 4], jnp.int32)
    got, want = both(data, ctx, base, 16)
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_single_token_context_is_v_row(data):
    """base=0, ctx=1: softmax over one ring position — output must be
    (approximately, interpret-mode bf16 dots) the ring v row 0."""
    q, ck, cv, rk, rv = data
    base = jnp.zeros(B, jnp.int32)
    ctx = jnp.ones(B, jnp.int32)
    got = flash_decode_attention(
        q, ck, cv, rk, rv, jnp.int32(1), ctx, base,
        chunk=32, interpret=True,
    )
    for b in range(B):
        for n in range(NH):
            h = n // (NH // NKV)
            np.testing.assert_allclose(
                np.asarray(got)[b, n], np.asarray(rv)[1, h, b, 0],
                rtol=5e-3, atol=5e-3,
            )


def test_chunk_boundary_contexts(data):
    """Ring bases straddling chunk boundaries agree with the reference
    (the per-slot DMA-skip index math)."""
    for bases in ([15, 16, 17, 31], [32, 33, 48, 60]):
        base = jnp.asarray(bases, jnp.int32)
        ctx = base + 3
        got, want = both(data, ctx, base, 16)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


# --- in-kernel int8 decode ctx (PR 14) -------------------------------

def _quantize_ctx(x, group):
    """Per-(layer, slot-lane, group) absmax int8 — the ctx scale grid
    models/llama.init_ctx uses (no kvh axis, group == page_size)."""
    lyr, kvh, lanes, s, hd = x.shape
    ng = s // group
    grouped = np.asarray(x).reshape(lyr, kvh, lanes, ng, group, hd)
    absmax = np.abs(grouped).max(axis=(1, 4, 5))        # [L, lanes, nG]
    scale = np.maximum(absmax / 127.0, 1e-8).astype(np.float32)
    q = np.clip(
        np.rint(grouped / scale[:, None, :, :, None, None]), -127, 127
    ).astype(np.int8).reshape(x.shape)
    return jnp.asarray(q), jnp.asarray(scale)


def _quant_args(data, group):
    q, ck, cv, rk, rv = data
    ck_q, ks = _quantize_ctx(ck, group)
    cv_q, vs = _quantize_ctx(cv, group)
    return q, ck_q, cv_q, rk, rv, ks, vs


@pytest.mark.parametrize("group", [16, 64])   # nG in {4, 1}
@pytest.mark.parametrize("chunk", [16, 32, 64])
def test_int8_kernel_matches_reference(data, group, chunk):
    """Quantized kernel (in-VMEM dequant after the chunk DMA) vs the
    quantized pure-jnp reference, across scale-group widths, chunking,
    and odd ctx/ring_base straddles. Pinned at 1e-2 abs by ISSUE 14
    (interpret mode lands ~1e-6)."""
    q, ck_q, cv_q, rk, rv, ks, vs = _quant_args(data, group)
    for bases in ([1, 15, 31, 60], [15, 16, 17, 33]):
        base = jnp.asarray(bases, jnp.int32)
        ctx = base + 2
        for layer in (0, L - 1):
            got = flash_decode_attention(
                q, ck_q, cv_q, rk, rv, jnp.int32(layer), ctx, base,
                chunk=chunk, interpret=True,
                ctx_k_scale=ks, ctx_v_scale=vs,
            )
            want = flash_decode_attention_reference(
                q, ck_q, cv_q, rk, rv, jnp.int32(layer), ctx, base,
                ctx_k_scale=ks, ctx_v_scale=vs,
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-2, rtol=0)


@pytest.mark.parametrize("sb", [2, 4])
def test_int8_kernel_slot_blocked(data, sb):
    """slot_block > 1 groups lanes per grid invocation; the quantized
    DMA-skip/scale index math must clamp identically."""
    q, ck_q, cv_q, rk, rv, ks, vs = _quant_args(data, 16)
    base = jnp.asarray([3, 17, 31, 59], jnp.int32)
    ctx = base + 2
    got = flash_decode_attention(
        q, ck_q, cv_q, rk, rv, jnp.int32(1), ctx, base,
        chunk=16, slot_block=sb, interpret=True,
        ctx_k_scale=ks, ctx_v_scale=vs,
    )
    want = flash_decode_attention_reference(
        q, ck_q, cv_q, rk, rv, jnp.int32(1), ctx, base,
        ctx_k_scale=ks, ctx_v_scale=vs,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-2, rtol=0)


def test_int8_dequant_error_bound(data):
    """Per-element dequantization error of the ctx payload is bounded by
    absmax/127 per (layer, lane, group) — the int8 quantizer invariant
    every upstream writer (prefill store, ring flush, seal) relies on."""
    _, ck, _, _, _ = data
    for group in (16, 64):
        ck_q, ks = _quantize_ctx(ck, group)
        ng = S // group
        deq = (np.asarray(ck_q, np.float32)
               .reshape(L, NKV, B + 1, ng, group, HD)
               * np.asarray(ks)[:, None, :, :, None, None])
        orig = np.asarray(ck).reshape(L, NKV, B + 1, ng, group, HD)
        bound = np.asarray(ks) * 0.5 + 1e-6   # scale = absmax/127
        err = np.abs(deq - orig).max(axis=(1, 4, 5))
        assert (err <= bound).all()


def test_int8_output_close_to_dense(data):
    """Quantized attention stays close to the bf16/f32 dense path: the
    quant noise per KV element is <= absmax/127 (~0.01 for this data),
    so the attention output — a convex combination of V rows — moves by
    the same order."""
    q, ck, cv, rk, rv = data
    _, ck_q, cv_q, _, _, ks, vs = _quant_args(data, 16)
    base = jnp.asarray([7, 21, 40, 61], jnp.int32)
    ctx = base + 2
    dense = flash_decode_attention_reference(
        q, ck, cv, rk, rv, jnp.int32(0), ctx, base)
    quant = flash_decode_attention_reference(
        q, ck_q, cv_q, rk, rv, jnp.int32(0), ctx, base,
        ctx_k_scale=ks, ctx_v_scale=vs)
    np.testing.assert_allclose(
        np.asarray(quant), np.asarray(dense), atol=0.08, rtol=0)


def test_pick_chunk():
    """_pick_chunk replaces the old gcd() fallback: honor exact
    requests, else the largest divisor <= want that is a multiple of
    the scale group, promoted past the grid-overhead floor."""
    assert _pick_chunk(64, 512) == 64        # clamp to S
    assert _pick_chunk(64, 16) == 16         # exact tile honored
    assert _pick_chunk(512, 512) == 512
    # non-tiling want: largest divisor <= want, floored at 128 (the old
    # gcd(512, 520) == 8 cliff)
    assert _pick_chunk(520, 512) == 260
    assert _pick_chunk(520, 512, 8) == 520   # group forces whole-S
    assert _pick_chunk(64, 16, 64) == 64     # group > want promotes
    # result always tiles S and the group
    for s, want, step in ((520, 512, 8), (192, 100, 16), (96, 64, 32)):
        c = _pick_chunk(s, want, step)
        assert s % c == 0 and c % step == 0
