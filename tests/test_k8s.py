"""Kubernetes integration tests (reference kubernetes_connector.py:79 +
operator manifests): the planner's KubernetesConnector against a FAKE
k8s API server, and serve-graph -> manifest rendering."""
import json

import pytest
from aiohttp import web

from dynamo_tpu.k8s import (
    KubernetesConnector,
    emit_k8s_manifests,
    render_manifests,
)


class FakeKubeApi:
    """Minimal apps/v1 scale subresource."""

    def __init__(self, replicas=2):
        self.replicas = replicas
        self.patches: list[dict] = []
        self.auth_headers: list[str] = []
        app = web.Application()
        app.router.add_get(
            "/apis/apps/v1/namespaces/{ns}/deployments/{name}/scale",
            self.get_scale,
        )
        app.router.add_patch(
            "/apis/apps/v1/namespaces/{ns}/deployments/{name}/scale",
            self.patch_scale,
        )
        self.app = app

    def _body(self, request):
        return {
            "kind": "Scale",
            "metadata": {
                "name": request.match_info["name"],
                "namespace": request.match_info["ns"],
            },
            "spec": {"replicas": self.replicas},
            "status": {"replicas": self.replicas},
        }

    async def get_scale(self, request):
        self.auth_headers.append(request.headers.get("Authorization", ""))
        if request.match_info["name"] == "missing":
            return web.json_response(
                {"message": "deployments.apps \"missing\" not found"},
                status=404,
            )
        return web.json_response(self._body(request))

    async def patch_scale(self, request):
        patch = json.loads(await request.text())
        self.patches.append(patch)
        self.replicas = int(patch["spec"]["replicas"])
        return web.json_response(self._body(request))


async def start_fake_api():
    """(api, base_url, server) — conftest's asyncio shim has no async
    fixtures, so tests start/stop the server explicitly."""
    from aiohttp.test_utils import TestServer

    api = FakeKubeApi()
    server = TestServer(api.app)
    await server.start_server()
    return api, f"http://{server.host}:{server.port}", server


async def test_connector_scale_cycle():
    api, base, server = await start_fake_api()
    conn = KubernetesConnector(
        "decode-workers", "prod", api_base=base, token="tok123"
    )
    try:
        await conn.start()
        assert conn.current_replicas() == 2
        await conn.set_replicas(5)
        assert conn.current_replicas() == 5
        assert api.replicas == 5
        assert api.patches == [{"spec": {"replicas": 5}}]
        # bearer token attached
        assert "Bearer tok123" in api.auth_headers
        # refresh observes out-of-band changes
        api.replicas = 3
        assert await conn.refresh() == 3
    finally:
        await conn.close()
        await server.close()


async def test_connector_propagates_api_errors():
    api, base, server = await start_fake_api()
    conn = KubernetesConnector("missing", "prod", api_base=base)
    try:
        with pytest.raises(RuntimeError, match="not found"):
            await conn.refresh()
    finally:
        await conn.close()
        await server.close()


async def test_connector_drives_planner_decide():
    """The connector satisfies the planner's Connector protocol end to
    end: a scale-up decision patches the Deployment."""
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats,
    )
    from dynamo_tpu.planner import Planner, PlannerConfig

    api, base, server = await start_fake_api()
    conn = KubernetesConnector("w", "ns", api_base=base)
    try:
        await conn.start()
        planner = Planner(
            kv=None, connector=conn,
            config=PlannerConfig(stable_intervals=1, max_replicas=8),
        )
        planner.aggregator.update(ForwardPassMetrics(
            worker_id="w0",
            worker_stats=WorkerStats(request_active_slots=8,
                                     request_total_slots=8,
                                     num_requests_waiting=9),
            kv_stats=KvStats(kv_active_blocks=95, kv_total_blocks=100,
                             gpu_cache_usage_perc=0.95),
        ))
        target = await planner.adjust()
        assert target == 3  # 2 observed + 1 scale-up step
        assert api.replicas == 3
    finally:
        await conn.close()
        await server.close()


# ---------------------------------------------------------------------------
# manifest generation


GRAPH = {
    "namespace": "dyn",
    "control_plane": {"port": 7111},
    "frontend": {"http_port": 8080},
    "workers": [
        {"name": "decode", "replicas": 2,
         "args": ["out=tpu", "--model-config", "llama3_1b",
                  "--model-name", "m"], "tpu_chips": 1},
        {"name": "prefill", "replicas": 1, "args": ["out=tpu"]},
    ],
    "planner": {"min_replicas": 1, "max_replicas": 4},
}


def test_emit_k8s_manifests_shapes():
    ms = emit_k8s_manifests(GRAPH, image="repo/dynamo-tpu:v1",
                            k8s_namespace="prod")
    kinds = [(m["kind"], m["metadata"]["name"]) for m in ms]
    assert ("Deployment", "dyn-store") in kinds
    assert ("Service", "dyn-store") in kinds
    assert ("Deployment", "dyn-frontend") in kinds
    assert ("Service", "dyn-frontend") in kinds
    assert ("Deployment", "dyn-decode") in kinds
    assert ("Deployment", "dyn-prefill") in kinds
    assert ("Deployment", "dyn-planner") in kinds

    by_name = {m["metadata"]["name"]: m for m in ms
               if m["kind"] == "Deployment"}
    decode = by_name["dyn-decode"]
    assert decode["spec"]["replicas"] == 2
    c = decode["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "repo/dynamo-tpu:v1"
    # workers point at the store service, not localhost
    assert "dyn-store:7111" in c["args"]
    assert c["resources"]["limits"]["google.com/tpu"] == 1
    # every object lands in the requested k8s namespace
    assert all(m["metadata"]["namespace"] == "prod" for m in ms)
    # planner flags carried through
    planner = by_name["dyn-planner"]
    pargs = planner["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--min-replicas" in pargs and "--max-replicas" in pargs


def test_emit_k8s_external_store_skips_store_deployment():
    graph = dict(GRAPH, control_plane={"external": "etcd.infra:7111"})
    ms = emit_k8s_manifests(graph)
    names = [m["metadata"]["name"] for m in ms]
    assert "dyn-store" not in names
    fe = next(m for m in ms if m["metadata"]["name"] == "dyn-frontend"
              and m["kind"] == "Deployment")
    args = fe["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "etcd.infra:7111" in args


def test_render_manifests_yaml_roundtrip():
    ms = emit_k8s_manifests(GRAPH)
    text = render_manifests(ms)
    try:
        import yaml

        docs = [d for d in yaml.safe_load_all(text) if d]
        assert len(docs) == len(ms)
        assert docs[0]["apiVersion"] in ("apps/v1", "v1")
    except ImportError:
        assert '"kind": "Deployment"' in text


class FakeObjectApi:
    """Minimal typed-object CRUD (apps/v1 deployments + v1 services) with
    labelSelector list — what the operator reconciles against."""

    def __init__(self):
        self.objects = {"deployments": {}, "services": {}}
        self.rv = 0
        app = web.Application()
        for coll, path in (
            ("deployments", "/apis/apps/v1/namespaces/{ns}/deployments"),
            ("services", "/api/v1/namespaces/{ns}/services"),
        ):
            app.router.add_get(path, self._mk_list(coll))
            app.router.add_post(path, self._mk_create(coll))
            app.router.add_put(path + "/{name}", self._mk_replace(coll))
            app.router.add_delete(path + "/{name}", self._mk_delete(coll))
        self.app = app

    def _mk_list(self, coll):
        async def handler(request):
            sel = request.query.get("labelSelector", "")
            items = []
            for obj in self.objects[coll].values():
                labels = obj.get("metadata", {}).get("labels", {})
                ok = all(
                    labels.get(k) == v
                    for k, _, v in (s.partition("=") for s in sel.split(",") if s)
                )
                if ok:
                    items.append(obj)
            return web.json_response({"items": items})
        return handler

    def _mk_create(self, coll):
        async def handler(request):
            obj = json.loads(await request.text())
            name = obj["metadata"]["name"]
            if name in self.objects[coll]:
                return web.json_response(
                    {"message": "already exists"}, status=409)
            self.rv += 1
            obj["metadata"]["resourceVersion"] = str(self.rv)
            self.objects[coll][name] = obj
            return web.json_response(obj, status=201)
        return handler

    def _mk_replace(self, coll):
        async def handler(request):
            obj = json.loads(await request.text())
            name = request.match_info["name"]
            if name not in self.objects[coll]:
                return web.json_response({"message": "not found"}, status=404)
            self.rv += 1
            obj["metadata"]["resourceVersion"] = str(self.rv)
            self.objects[coll][name] = obj
            return web.json_response(obj)
        return handler

    def _mk_delete(self, coll):
        async def handler(request):
            self.objects[coll].pop(request.match_info["name"], None)
            return web.json_response({})
        return handler


async def start_fake_object_api():
    from aiohttp.test_utils import TestServer

    api = FakeObjectApi()
    server = TestServer(api.app)
    await server.start_server()
    return api, f"http://{server.host}:{server.port}", server


OP_GRAPH = {
    "namespace": "dyn",
    "frontend": {"http_port": 8080},
    "workers": [
        {"name": "decode", "replicas": 2, "tpu_chips": 4,
         "args": ["out=tpu", "--model-config", "llama3_1b"]},
    ],
}


async def test_operator_reconcile_create_update_delete():
    """Spec change -> rollout; worker removal -> orphan deletion; no-op
    pass -> all unchanged (reference operator controller semantics)."""
    from dynamo_tpu.k8s import DynamoOperator

    api, base, server = await start_fake_object_api()
    op = DynamoOperator(api_base=base, verify_ssl=False,
                        k8s_namespace="default")
    try:
        c = await op.reconcile(OP_GRAPH)
        assert c["created"] >= 4 and c["deleted"] == 0  # store+fe+svc+worker
        assert "dyn-decode" in api.objects["deployments"]
        assert api.objects["deployments"]["dyn-decode"]["spec"]["replicas"] == 2

        # idempotent second pass
        c = await op.reconcile(OP_GRAPH)
        assert c["created"] == 0 and c["updated"] == 0 and c["deleted"] == 0

        # spec change rolls the deployment
        g2 = json.loads(json.dumps(OP_GRAPH))
        g2["workers"][0]["args"].append("--max-decode-slots")
        g2["workers"][0]["args"].append("16")
        c = await op.reconcile(g2)
        assert c["updated"] == 1
        args = api.objects["deployments"]["dyn-decode"]["spec"]["template"][
            "spec"]["containers"][0]["args"]
        assert "--max-decode-slots" in args

        # removing the worker deletes its deployment, keeps the rest
        g3 = json.loads(json.dumps(OP_GRAPH))
        g3["workers"] = []
        c = await op.reconcile(g3)
        assert c["deleted"] == 1
        assert "dyn-decode" not in api.objects["deployments"]
        assert "dyn-frontend" in api.objects["deployments"]
    finally:
        await op.close()
        await server.close()


async def test_operator_watches_store_spec():
    """The graph spec is a store key (the CRD analogue): writing it
    triggers a reconcile; updating it triggers a rollout."""
    import asyncio

    from dynamo_tpu.k8s import DynamoOperator, graph_key
    from dynamo_tpu.runtime.client import KvClient
    from dynamo_tpu.runtime.store import serve_store

    api, base, server = await start_fake_object_api()
    st_server, _ = await serve_store(port=0, sweep_interval_s=0.1)
    port = st_server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    kv2 = await KvClient(port=port).connect()
    op = DynamoOperator(api_base=base, verify_ssl=False, resync_s=5.0)
    task = asyncio.ensure_future(op.run(kv, "dyn"))
    try:
        await kv2.put(graph_key("dyn"), json.dumps(OP_GRAPH))
        for _ in range(100):
            if "dyn-decode" in api.objects["deployments"]:
                break
            await asyncio.sleep(0.05)
        assert "dyn-decode" in api.objects["deployments"]

        g2 = json.loads(json.dumps(OP_GRAPH))
        g2["workers"][0]["replicas"] = 5
        await kv2.put(graph_key("dyn"), json.dumps(g2))
        for _ in range(100):
            d = api.objects["deployments"].get("dyn-decode", {})
            if d.get("spec", {}).get("replicas") == 5:
                break
            await asyncio.sleep(0.05)
        assert api.objects["deployments"]["dyn-decode"]["spec"]["replicas"] == 5
    finally:
        task.cancel()
        await op.close()
        await kv.close()
        await kv2.close()
        st_server.close()
        await server.close()
