"""Golden tests: our paged-KV llama forward vs HuggingFace transformers.

The reference gets model correctness for free from vLLM; we validate ours
against the HF torch implementation on a tiny random-init config (float32 so
comparisons are tight). Covers: full prefill, paged decode steps, prefix-hit
continuation prefill, and GSPMD-sharded execution on the CPU test mesh.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

PAGE = 8
MAX_PAGES = 8  # covers 64 tokens


@pytest.fixture(scope="module")
def pair():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = ModelConfig.tiny(dtype="float32")
    hf_cfg = LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rms_norm_eps=cfg.rms_norm_eps,
        rope_theta=cfg.rope_theta,
        max_position_embeddings=cfg.max_position_embeddings,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    params = llama.params_from_state_dict(cfg, sd, dtype="float32")
    return cfg, model, params


def hf_logits(model, tokens: list[int]) -> np.ndarray:
    with torch.no_grad():
        out = model(torch.tensor([tokens])).logits
    return out[0].float().numpy()  # [T, V]


def pad_to(tokens: list[int], mult: int) -> np.ndarray:
    t = list(tokens)
    while len(t) % mult:
        t.append(0)
    return np.asarray(t, np.int32)


def test_prefill_matches_hf(pair):
    cfg, model, params = pair
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, cfg.vocab_size, size=21).tolist()

    cache = llama.init_cache(cfg, num_pages=16, page_size=PAGE, dtype=jnp.float32)
    page_table = np.zeros(MAX_PAGES, np.int32)
    page_table[:3] = [1, 2, 3]  # 21 tokens -> 3 pages (page 0 reserved)

    cache, logits = llama.prefill(
        cfg, params, cache,
        jnp.asarray(pad_to(prompt, PAGE)),
        jnp.asarray(page_table),
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    ref = hf_logits(model, prompt)[-1]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-3, atol=2e-3)


def test_decode_matches_hf(pair):
    cfg, model, params = pair
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, cfg.vocab_size, size=13).tolist()

    cache = llama.init_cache(cfg, num_pages=16, page_size=PAGE, dtype=jnp.float32)
    pt = np.zeros(MAX_PAGES, np.int32)
    pt[:4] = [1, 2, 3, 4]
    cache, logits = llama.prefill(
        cfg, params, cache,
        jnp.asarray(pad_to(prompt, PAGE)),
        jnp.asarray(pt), jnp.int32(0), jnp.int32(len(prompt)),
    )

    # decode 6 tokens greedily with B=2 slots; slot 1 inactive. Rounds of
    # R=2 ring steps followed by a flush — exercises the two-tier decode
    # (ring attention within a round, pool after flush).
    B, R = 2, 2
    page_tables = np.zeros((B, MAX_PAGES), np.int32)
    page_tables[0] = pt
    ptd = jnp.asarray(page_tables)
    ring = llama.init_ring(cfg, B, R, dtype=jnp.float32)
    seq = list(prompt)
    tok = int(np.argmax(np.asarray(logits)))
    for round_start in range(0, 6, R):
        ring_base = jnp.asarray([len(seq), 0], jnp.int32)  # pos of ring slot 0
        for s in range(R):
            seq.append(tok)
            tokens = jnp.asarray([tok, 0], jnp.int32)
            ctx = jnp.asarray([len(seq), 1], jnp.int32)
            ring, logits = llama.decode_step(
                cfg, params, cache, ring, tokens, ptd, ctx,
                ring_base, jnp.int32(s),
            )
            ref = hf_logits(model, seq)[-1]
            got = np.asarray(logits)[0]
            np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
            tok = int(np.argmax(got))
        cache = llama.flush(
            cfg, cache, ring, ptd, ring_base,
            jnp.asarray([R, 0], jnp.int32),
        )


def test_prefix_continuation_matches_hf(pair):
    """Prefix-cache hit path: prefill 16 cached tokens, then continue with 5
    new ones; logits must equal a fresh full-21-token forward."""
    cfg, model, params = pair
    rng = np.random.RandomState(3)
    full = rng.randint(1, cfg.vocab_size, size=21).tolist()

    cache = llama.init_cache(cfg, num_pages=16, page_size=PAGE, dtype=jnp.float32)
    pt = np.zeros(MAX_PAGES, np.int32)
    pt[:3] = [5, 6, 7]
    # stage 1: the "cached prefix" (16 tokens = 2 pages, page-aligned)
    cache, _ = llama.prefill(
        cfg, params, cache,
        jnp.asarray(pad_to(full[:16], PAGE)),
        jnp.asarray(pt), jnp.int32(0), jnp.int32(16),
    )
    # stage 2: continuation of the remaining 5 tokens
    cache, logits = llama.prefill(
        cfg, params, cache,
        jnp.asarray(pad_to(full[16:], PAGE)),
        jnp.asarray(pt), jnp.int32(16), jnp.int32(21),
    )
    ref = hf_logits(model, full)[-1]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-3, atol=2e-3)


def test_sharded_prefill_matches_unsharded(pair):
    """TP=2 GSPMD execution must be numerically equivalent (CPU mesh)."""
    cfg, _, params = pair
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    shardings = llama.param_shardings(cfg, mesh)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )
    cache = llama.init_cache(cfg, num_pages=16, page_size=PAGE, dtype=jnp.float32)
    cache_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        llama.init_cache(cfg, num_pages=16, page_size=PAGE, dtype=jnp.float32),
        llama.cache_shardings(cfg, mesh),
    )
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, cfg.vocab_size, size=10).tolist()
    pt = np.zeros(MAX_PAGES, np.int32)
    pt[:2] = [1, 2]
    args = (
        jnp.asarray(pad_to(prompt, PAGE)), jnp.asarray(pt),
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    _, ref = llama.prefill(cfg, params, cache, *args)
    with mesh:
        _, got = llama.prefill(cfg, params_sh, cache_sh, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)
