"""Golden tests: our contiguous-ctx llama forward vs HuggingFace.

The reference gets model correctness for free from vLLM; we validate ours
against the HF torch implementation on a tiny random-init config (float32 so
comparisons are tight). Covers: full prefill, decode steps, prefix-hit
continuation prefill, pool<->ctx copies (load_ctx_pages/seal_blocks), and
GSPMD-sharded execution on the CPU test mesh.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

PAGE = 8
S_MAX = 64


@pytest.fixture(scope="module")
def pair():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = ModelConfig.tiny(dtype="float32")
    hf_cfg = LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rms_norm_eps=cfg.rms_norm_eps,
        rope_theta=cfg.rope_theta,
        max_position_embeddings=cfg.max_position_embeddings,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    params = llama.params_from_state_dict(cfg, sd, dtype="float32")
    return cfg, model, params


def hf_logits(model, tokens: list[int]) -> np.ndarray:
    with torch.no_grad():
        out = model(torch.tensor([tokens])).logits
    return out[0].float().numpy()  # [T, V]


def pad_to(tokens: list[int], mult: int) -> np.ndarray:
    t = list(tokens)
    while len(t) % mult:
        t.append(0)
    return np.asarray(t, np.int32)


def test_prefill_matches_hf(pair):
    cfg, model, params = pair
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, cfg.vocab_size, size=21).tolist()

    ctx = llama.init_ctx(cfg, 1, S_MAX, dtype=jnp.float32)
    ctx, logits = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(prompt, PAGE)),
        jnp.int32(0), jnp.int32(0), jnp.int32(len(prompt)),
    )
    ref = hf_logits(model, prompt)[-1]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-3, atol=2e-3)


def test_decode_matches_hf(pair):
    cfg, model, params = pair
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, cfg.vocab_size, size=13).tolist()

    # B=2 slots; slot 1 inactive (scratch-destined garbage lane)
    B = 2
    ctx = llama.init_ctx(cfg, B, S_MAX, dtype=jnp.float32)
    ctx, logits = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(prompt, PAGE)),
        jnp.int32(0), jnp.int32(0), jnp.int32(len(prompt)),
    )
    seq = list(prompt)
    tok = int(np.argmax(np.asarray(logits)))
    R = 2  # rounds of 2 ring steps then a flush: exercises both tiers
    ring = llama.init_ring(cfg, B, R, dtype=jnp.float32)
    dest = jnp.asarray([0, B], jnp.int32)  # slot 1 -> scratch lane
    for _ in range(3):
        ring_base = jnp.asarray([len(seq), 0], jnp.int32)
        for s in range(R):
            seq.append(tok)
            tokens = jnp.asarray([tok, 0], jnp.int32)
            ctx_lens = jnp.asarray([len(seq), 1], jnp.int32)
            ring, logits = llama.decode_step(
                cfg, params, ctx, ring, tokens, ctx_lens,
                ring_base, jnp.int32(s),
            )
            ref = hf_logits(model, seq)[-1]
            got = np.asarray(logits)[0]
            np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
            tok = int(np.argmax(got))
        ctx = llama.flush_ctx(
            ctx, ring, dest, ring_base, jnp.asarray([R, 0], jnp.int32),
        )


def test_prefix_continuation_matches_hf(pair):
    """Prefix-cache hit path: prefill 16 cached tokens, then continue with 5
    new ones; logits must equal a fresh full-21-token forward."""
    cfg, model, params = pair
    rng = np.random.RandomState(3)
    full = rng.randint(1, cfg.vocab_size, size=21).tolist()

    ctx = llama.init_ctx(cfg, 1, S_MAX, dtype=jnp.float32)
    # stage 1: the "cached prefix" (16 tokens = 2 pages, page-aligned)
    ctx, _ = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(full[:16], PAGE)),
        jnp.int32(0), jnp.int32(0), jnp.int32(16),
    )
    # stage 2: continuation of the remaining 5 tokens
    ctx, logits = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(full[16:], PAGE)),
        jnp.int32(0), jnp.int32(16), jnp.int32(21),
    )
    ref = hf_logits(model, full)[-1]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-3, atol=2e-3)


def test_seal_and_reload_roundtrip(pair):
    """seal_blocks (ctx->pool) then load_ctx_pages (pool->ctx) on another
    lane must reproduce the continuation logits exactly — the admission/
    commit data path of the prefix cache."""
    cfg, model, params = pair
    rng = np.random.RandomState(5)
    full = rng.randint(1, cfg.vocab_size, size=21).tolist()

    ctx = llama.init_ctx(cfg, 2, S_MAX, dtype=jnp.float32)
    cache = llama.init_cache(cfg, num_pages=8, page_size=PAGE,
                             dtype=jnp.float32)
    # prefill the 16-token page-aligned prefix on lane 0
    ctx, _ = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(full[:16], PAGE)),
        jnp.int32(0), jnp.int32(0), jnp.int32(16),
    )
    # seal its two blocks into pool pages 3 and 4
    cache = llama.seal_blocks(
        cache, ctx,
        jnp.asarray([0, 0], jnp.int32),
        jnp.asarray([0, PAGE], jnp.int32),
        jnp.asarray([3, 4], jnp.int32),
        page_size=PAGE,
    )
    # load them into lane 1 and continue there
    ctx = llama.load_ctx_pages(
        ctx, cache, jnp.int32(1), jnp.asarray([3, 4], jnp.int32)
    )
    ctx, logits = llama.prefill(
        cfg, params, ctx,
        jnp.asarray(pad_to(full[16:], PAGE)),
        jnp.int32(1), jnp.int32(16), jnp.int32(21),
    )
    ref = hf_logits(model, full)[-1]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-3, atol=2e-3)


def test_sharded_prefill_matches_unsharded(pair):
    """TP=2 GSPMD execution must be numerically equivalent (CPU mesh)."""
    cfg, _, params = pair
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    shardings = llama.param_shardings(cfg, mesh)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )
    ctx = llama.init_ctx(cfg, 1, S_MAX, dtype=jnp.float32)
    ctx_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        llama.init_ctx(cfg, 1, S_MAX, dtype=jnp.float32),
        llama.ctx_shardings(cfg, mesh),
    )
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, cfg.vocab_size, size=10).tolist()
    args = (
        jnp.asarray(pad_to(prompt, PAGE)), jnp.int32(0),
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    _, ref = llama.prefill(cfg, params, ctx, *args)
    with mesh:
        _, got = llama.prefill(cfg, params_sh, ctx_sh, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_load_ctx_pages_pow2_clamp_at_bench_r05_shape():
    """Regression pin for the BENCH_r05 tail crash: a 46-page matched run
    pow2-padded to 64 pages (update span 64*64 = 4096 tokens) loaded into
    a ctx region of S = 3328 (52 pages) must clamp statically to the
    region — the unclamped dynamic_update_slice was a trace-time
    TypeError ("update shape must be smaller than operand shape ...
    (…, 4096, …) for operand (…, 3328, …)") that killed the whole engine
    round. Geometry is EXACTLY the r05 shape; L/kvh/hd are shrunk (the
    crash class lives on the page/region axes alone)."""
    L, kvh, hd = 1, 1, 4
    ps, S = 64, 3328          # 52-page region (r05 ctx region)
    n_real, pad_w = 46, 64    # 46 matched pages -> pow2_cover 64
    rng = np.random.RandomState(0)
    cache = {
        name: jnp.asarray(rng.standard_normal(
            (L, kvh, pad_w + 1, ps, hd)).astype(np.float32))
        for name in ("k", "v")
    }
    want = {name: np.asarray(cache[name][:, :, 1:n_real + 1]).reshape(
        L, kvh, n_real * ps, hd) for name in ("k", "v")}
    ctx = {name: jnp.zeros((L, kvh, 2, S, hd), jnp.float32)
           for name in ("k", "v")}
    padded = np.zeros(pad_w, np.int32)  # padding -> scratch page 0
    padded[:n_real] = np.arange(1, n_real + 1)
    out = llama.load_ctx_pages(
        ctx, cache, jnp.int32(0), jnp.asarray(padded)
    )
    for name in ("k", "v"):
        assert out[name].shape == (L, kvh, 2, S, hd)
        # every real matched page landed at its region position; only
        # the padding overflow (pages 53..64) was dropped
        np.testing.assert_array_equal(
            np.asarray(out[name][:, :, 0, : n_real * ps]), want[name]
        )
