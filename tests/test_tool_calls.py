"""Tool-call parsing + serving tests (reference protocols/openai tool
plumbing: tool_calls responses, finish_reason tool_calls, streamed
deltas)."""
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.frontend import HttpService, ModelManager
from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.tool_calls import ToolCallAccumulator, parse_tool_calls


# ---------------------------------------------------------------------------
# parser


def test_parse_llama3_json_single_and_array():
    calls = parse_tool_calls(
        ' {"name": "get_weather", "parameters": {"city": "SF"}} '
    )
    assert len(calls) == 1
    assert calls[0]["type"] == "function"
    assert calls[0]["function"]["name"] == "get_weather"
    assert json.loads(calls[0]["function"]["arguments"]) == {"city": "SF"}
    assert calls[0]["id"].startswith("call_")

    arr = parse_tool_calls(
        '[{"name": "a", "arguments": {}}, {"name": "b", "parameters": {"x": 1}}]'
    )
    assert [c["function"]["name"] for c in arr] == ["a", "b"]


def test_parse_hermes_tags():
    calls = parse_tool_calls(
        'something\n<tool_call>{"name": "f", "arguments": {"k": 2}}</tool_call>'
        '<tool_call>{"name": "g", "arguments": {}}</tool_call>'
    )
    assert [c["function"]["name"] for c in calls] == ["f", "g"]


def test_parse_rejects_non_tool_text():
    assert parse_tool_calls("hello there") is None
    assert parse_tool_calls('{"not": "a tool"}') is None
    assert parse_tool_calls('{"name": broken json') is None
    assert parse_tool_calls("<tool_call>{unterminated") is None
    assert parse_tool_calls("") is None


def test_accumulator_releases_plain_text_immediately():
    acc = ToolCallAccumulator()
    assert acc.feed("Hello") == "Hello"
    assert acc.feed(" world") == " world"
    calls, leftover = acc.finalize()
    assert calls is None and not leftover


def test_accumulator_buffers_and_parses_tool_call():
    acc = ToolCallAccumulator()
    assert acc.feed('{"name": "f",') == ""
    assert acc.feed(' "parameters": {}}') == ""
    calls, leftover = acc.finalize()
    assert calls is not None and calls[0]["function"]["name"] == "f"
    assert not leftover


def test_accumulator_releases_failed_parse_as_content():
    acc = ToolCallAccumulator()
    assert acc.feed("{oops not json") == ""
    calls, leftover = acc.finalize()
    assert calls is None and leftover == "{oops not json"


# ---------------------------------------------------------------------------
# service level (fake chain emitting text deltas)


class _TextChain:
    """Chain stub: emits scripted text deltas (what a template-driven
    model would generate for a tool prompt)."""

    name = "toolm"
    chat = True
    completions = True

    def __init__(self, pieces):
        self.pieces = pieces

    def preprocess(self, req):
        from dynamo_tpu.protocols.common import PreprocessedRequest

        return PreprocessedRequest(token_ids=[1, 2, 3])

    def generate(self, pre):
        async def run():
            for p in self.pieces:
                yield LLMEngineOutput(token_ids=[0], text=p)
            yield LLMEngineOutput(token_ids=[], finish_reason=FinishReason.EOS)

        return run()


def make_service(pieces):
    manager = ModelManager()
    manager.register(_TextChain(pieces))
    return HttpService(manager)


TOOLS = [{"type": "function",
          "function": {"name": "get_weather", "parameters": {}}}]


async def test_unary_chat_tool_calls():
    svc = make_service(['{"name": "get_weather", ', '"parameters": {"c": 1}}'])
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/chat/completions", json={
        "model": "toolm",
        "messages": [{"role": "user", "content": "weather?"}],
        "tools": TOOLS,
    })
    body = await r.json()
    choice = body["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    assert choice["message"]["content"] is None
    call = choice["message"]["tool_calls"][0]
    assert call["function"]["name"] == "get_weather"
    assert json.loads(call["function"]["arguments"]) == {"c": 1}

    # without tools declared, the same text is plain content
    r = await client.post("/v1/chat/completions", json={
        "model": "toolm",
        "messages": [{"role": "user", "content": "weather?"}],
    })
    body = await r.json()
    assert body["choices"][0]["finish_reason"] == "stop"
    assert body["choices"][0]["message"]["content"].startswith('{"name"')
    await client.close()


async def test_streaming_chat_tool_calls():
    svc = make_service(['{"name": "get_weather", ', '"parameters": {}}'])
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/chat/completions", json={
        "model": "toolm",
        "messages": [{"role": "user", "content": "weather?"}],
        "tools": TOOLS,
        "stream": True,
    })
    dec = SseDecoder()
    content_chunks, tool_deltas, finish = [], [], None
    for ev in dec.feed(await r.read()):
        if ev.is_done:
            continue
        chunk = json.loads(ev.data)
        for c in chunk.get("choices", []):
            if c.get("delta", {}).get("content"):
                content_chunks.append(c["delta"]["content"])
            if c.get("delta", {}).get("tool_calls"):
                tool_deltas.extend(c["delta"]["tool_calls"])
            if c.get("finish_reason"):
                finish = c["finish_reason"]
    assert content_chunks == []          # tool text never leaked as content
    assert finish == "tool_calls"
    assert tool_deltas[0]["function"]["name"] == "get_weather"
    await client.close()


async def test_streaming_plain_text_with_tools_declared():
    """Tools declared but the model answers normally: content streams
    through (after the undecided first char resolves)."""
    svc = make_service(["Sunny ", "today."])
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/chat/completions", json={
        "model": "toolm",
        "messages": [{"role": "user", "content": "weather?"}],
        "tools": TOOLS,
        "stream": True,
    })
    dec = SseDecoder()
    text, finish = "", None
    for ev in dec.feed(await r.read()):
        if ev.is_done:
            continue
        chunk = json.loads(ev.data)
        for c in chunk.get("choices", []):
            text += c.get("delta", {}).get("content") or ""
            if c.get("finish_reason"):
                finish = c["finish_reason"]
    assert text == "Sunny today."
    assert finish == "stop"
    await client.close()


def test_parse_strict_rejects_content_objects_and_unknown_names():
    # extra keys: a content object that merely HAS "name" is not a call
    assert parse_tool_calls('{"name": "Alice", "age": 30}') is None
    # declared-name validation
    assert parse_tool_calls('{"name": "evil", "arguments": {}}',
                            allowed={"get_weather"}) is None
    assert parse_tool_calls('{"name": "get_weather", "arguments": {}}',
                            allowed={"get_weather"}) is not None


def test_parse_hermes_preserves_surrounding_prose():
    from dynamo_tpu.tool_calls import parse_tool_calls_with_content

    calls, content = parse_tool_calls_with_content(
        "Let me check.\n"
        '<tool_call>{"name": "f", "arguments": {}}</tool_call>'
        "\nOne moment."
    )
    assert calls and calls[0]["function"]["name"] == "f"
    assert "Let me check." in content and "One moment." in content


def test_accumulator_releases_diverged_tag_early():
    acc = ToolCallAccumulator()
    # '<p' diverges from '<tool_call>' at the 2nd char -> released at once
    assert acc.feed("<p>") == "<p>"
    assert acc.feed("hello") == "hello"
    calls, leftover = acc.finalize()
    assert calls is None and not leftover


def test_accumulator_releases_non_tool_json_once_complete():
    acc = ToolCallAccumulator()
    assert acc.feed('{"answer":') == ""
    out = acc.feed(' 42}')
    assert out == '{"answer": 42}'         # complete non-tool JSON released
    calls, leftover = acc.finalize()
    assert calls is None and leftover is None


def test_accumulator_catches_mid_stream_hermes_tag():
    acc = ToolCallAccumulator()
    released = acc.feed("Okay. ")
    released += acc.feed('<tool_call>{"name": "f", ')
    released += acc.feed('"arguments": {}}</tool_call>')
    assert released.startswith("Okay. ")
    assert "<tool_call>" not in released
    calls, leftover = acc.finalize()
    assert calls is not None and calls[0]["function"]["name"] == "f"
