"""OpenAI Responses API tests (reference protocols/openai/responses.rs):
unary + streamed typed events + validation, over the echo engine."""
import json

from tests.test_http_service import make_echo_service, with_client


async def test_responses_unary():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/responses",
        json={"model": "echo", "input": "hello world", "max_output_tokens": 2},
    )
    assert r.status == 200
    body = await r.json()
    assert body["object"] == "response"
    assert body["status"] == "incomplete"  # ran into max_output_tokens
    assert body["incomplete_details"] == {"reason": "max_output_tokens"}
    msg = body["output"][0]
    assert msg["type"] == "message" and msg["role"] == "assistant"
    assert msg["content"][0]["type"] == "output_text"
    assert msg["content"][0]["text"].strip() == "hello world"
    assert body["usage"]["output_tokens"] == 2
    assert body["usage"]["input_tokens"] > 0
    await client.close()


async def test_responses_message_array_and_instructions():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/responses",
        json={
            "model": "echo",
            "instructions": "hello",
            "input": [
                {"type": "message", "role": "user",
                 "content": [{"type": "input_text", "text": "world"}]},
            ],
            "max_output_tokens": 2,
        },
    )
    assert r.status == 200
    body = await r.json()
    # echo returns the formatted prompt: instructions + input concatenated
    assert body["output"][0]["content"][0]["text"].strip() == "hello world"
    await client.close()


async def test_responses_streaming_events():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/responses",
        json={"model": "echo", "input": "hello world",
              "max_output_tokens": 2, "stream": True},
    )
    assert r.status == 200
    raw = (await r.content.read()).decode()
    events = []
    for block in raw.split("\n\n"):
        lines = dict(
            ln.split(": ", 1) for ln in block.splitlines() if ": " in ln
        )
        if "event" in lines:
            events.append((lines["event"], json.loads(lines["data"])))
    kinds = [k for k, _ in events]
    assert kinds[0] == "response.created"
    assert events[0][1]["response"]["status"] == "in_progress"
    assert "response.output_text.delta" in kinds
    assert kinds[-2] == "response.output_text.done"
    assert kinds[-1] == "response.incomplete"  # hit max_output_tokens
    text = "".join(d["delta"] for k, d in events
                   if k == "response.output_text.delta")
    assert text.strip() == "hello world"
    final = events[-1][1]["response"]
    assert final["output"][0]["content"][0]["text"].strip() == "hello world"
    await client.close()


async def test_responses_validation():
    client = await with_client(make_echo_service())
    # empty input
    r = await client.post("/v1/responses",
                          json={"model": "echo", "input": ""})
    assert r.status == 400
    # stateful chaining rejected
    r = await client.post(
        "/v1/responses",
        json={"model": "echo", "input": "x",
              "previous_response_id": "resp_123"},
    )
    assert r.status == 400
    # unknown model
    r = await client.post("/v1/responses",
                          json={"model": "nope", "input": "x"})
    assert r.status == 404
    await client.close()
