"""Expert-parallel MoE tests (SURVEY §2.5 EP/wide-EP row; reference does
this via SGLang+DeepEP — here shard_map + all_to_all over the ep axis,
tested on the virtual 8-device CPU mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    moe_layer,
    moe_params_shardings,
    moe_reference,
)


def ep_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("ep",))


def place(h, params, mesh):
    sh = moe_params_shardings(mesh)
    return (
        jax.device_put(h, NamedSharding(mesh, P("ep", None))),
        {k: jax.device_put(v, sh[k]) for k, v in params.items()},
    )


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_moe_matches_dense_reference(ep):
    """With ample capacity (no drops) the distributed dispatch must equal
    the dense single-device computation exactly."""
    cfg = MoEConfig(hidden_size=16, intermediate_size=32, num_experts=8,
                    top_k=2, capacity_factor=8.0)  # no overflow
    params = init_moe_params(cfg, 0)
    rng = np.random.default_rng(1)
    T = 32
    h = jnp.asarray(rng.standard_normal((T, 16)), jnp.float32)
    ref = moe_reference(h, params, cfg)

    mesh = ep_mesh(ep)
    hs, ps = place(h, params, mesh)
    out = moe_layer(hs, ps, cfg, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_moe_capacity_overflow_drops_not_corrupts():
    """Tiny capacity: overflowing tokens lose their expert contribution
    (GShard drop semantics) but never corrupt other tokens or NaN."""
    cfg = MoEConfig(hidden_size=8, intermediate_size=16, num_experts=4,
                    top_k=1, capacity_factor=0.25)  # force drops
    params = init_moe_params(cfg, 0)
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = ep_mesh(4)
    hs, ps = place(h, params, mesh)
    out = np.asarray(moe_layer(hs, ps, cfg, mesh))
    assert np.isfinite(out).all()
    # kept tokens match the reference; dropped ones are zero
    ref = np.asarray(moe_reference(h, params, cfg))
    per_tok = np.abs(out).sum(-1)
    kept = per_tok > 0
    assert kept.any()
    np.testing.assert_allclose(out[kept], ref[kept], rtol=2e-5, atol=2e-5)


def test_moe_validates_divisibility():
    cfg = MoEConfig(hidden_size=8, intermediate_size=16, num_experts=6)
    params = init_moe_params(cfg, 0)
    mesh = ep_mesh(4)
    h = jnp.zeros((16, 8), jnp.float32)
    with pytest.raises(ValueError, match="experts 6 not divisible"):
        moe_layer(h, params, cfg, mesh)
