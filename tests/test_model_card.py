"""Model card artifact tests (reference model_card/model.rs:256-305 —
upload at registration, download by filesystem-less frontends) and the
llmctl CLI (launch/llmctl)."""
import asyncio
import json
import os

import pytest

from dynamo_tpu.model_card import delete_card, download_card, upload_card
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store
from dynamo_tpu.tokenizer import HfTokenizer, make_test_tokenizer

WORDS = [f"w{i}" for i in range(50)]


def build_model_dir(tmp_path) -> str:
    """A minimal HF-style model dir around the test tokenizer."""
    d = tmp_path / "model"
    d.mkdir()
    tok = make_test_tokenizer(WORDS)
    tok._t.save(str(d / "tokenizer.json"))
    (d / "config.json").write_text(json.dumps(
        {"eos_token_id": 2, "bos_token_id": 1}
    ))
    return str(d)


async def start_store():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    return server, server.sockets[0].getsockname()[1]


async def test_card_upload_download_roundtrip(tmp_path):
    model_dir = build_model_dir(tmp_path)
    server, port = await start_store()
    kv = await KvClient(port=port).connect()

    bucket = await upload_card(kv, "ns", "m1", model_dir)
    assert bucket == "cards/ns/m1"

    dest = await download_card(kv, bucket, str(tmp_path / "dl"))
    assert dest is not None
    tok = HfTokenizer.from_dir(dest)
    orig = make_test_tokenizer(WORDS)
    assert tok.encode("w1 w2 w3") == orig.encode("w1 w2 w3")
    assert tok.eos_token_ids == [2]

    await delete_card(kv, bucket)
    assert await download_card(kv, bucket) is None
    # empty dir: nothing to upload
    empty = tmp_path / "empty"
    empty.mkdir()
    assert await upload_card(kv, "ns", "m2", str(empty)) is None
    await kv.close()
    server.close()


async def test_frontend_loads_tokenizer_from_card(tmp_path):
    """A frontend with NO filesystem access to the model dir loads the
    real tokenizer from the card artifacts (model.rs:305)."""
    from dynamo_tpu.frontend import ModelManager
    from dynamo_tpu.frontend.watcher import ModelEntry, ModelWatcher, register_llm
    from dynamo_tpu.mocker import MockerArgs, MockerEngine
    from dynamo_tpu.runtime.component import DistributedRuntime

    model_dir = build_model_dir(tmp_path)
    server, port = await start_store()
    rt = await DistributedRuntime.connect(port=port)
    eng = MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=4))
    entry = ModelEntry(name="cardm", namespace="cm", component="backend",
                       block_size=4, model_path=model_dir)
    served = await register_llm(rt, eng, entry)
    assert entry.card_ref == "cards/cm/cardm"

    # simulate a remote frontend: the worker's model_path doesn't exist
    # there — rewrite the registration with a bogus path
    key = f"dynamo://cm/_models/cardm/{served.lease_id}"
    entry2 = ModelEntry.from_json(entry.to_json())
    entry2.model_path = "/nonexistent/elsewhere"
    await rt.kv.put(key, entry2.to_json(), lease=served.lease_id)

    frt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    watcher = await ModelWatcher(frt, manager, namespace="cm").start()
    try:
        for _ in range(100):
            if len(manager) > 0:
                break
            await asyncio.sleep(0.05)
        chain = manager.get("cardm")
        # the REAL tokenizer came through the card, not make_test_tokenizer
        orig = make_test_tokenizer(WORDS)
        assert chain.preprocessor.tokenizer.encode("w7 w8") == \
            orig.encode("w7 w8")
    finally:
        await watcher.stop()
        await frt.close()
        await served.shutdown()
        await eng.stop()
        await rt.close()
        server.close()


async def test_llmctl_add_list_remove(capsys):
    from dynamo_tpu.cli import main as cli_main

    server, port = await start_store()
    cp = f"127.0.0.1:{port}"

    def run(*argv):
        # llmctl uses asyncio.run internally; hop to a thread to avoid
        # nesting loops
        return cli_main(["llmctl", "--control-plane", cp, *argv])

    rc = await asyncio.to_thread(run, "add", "ext-model",
                                 "--component", "extbackend")
    assert rc == 0
    rc = await asyncio.to_thread(run, "list")
    assert rc == 0
    out = capsys.readouterr().out
    assert "ext-model" in out and "extbackend" in out

    # static entries are discoverable by the watcher
    kv = await KvClient(port=port).connect()
    kvs = await kv.get_prefix("dynamo://dynamo/_models/")
    assert len(kvs) == 1 and kvs[0][0].endswith("/static")

    rc = await asyncio.to_thread(run, "remove", "ext-model")
    assert rc == 0
    assert await kv.get_prefix("dynamo://dynamo/_models/") == []
    await kv.close()
    server.close()
