"""Host-budget regression pins: the round-pipelining + segment-diet PR.

BENCH_r07 measured steady decode at 2.60 ms wall/step = 1.57 ms host +
1.03 ms device, fully serialized — the engine was HOST-bound. After the
double-buffered round pipeline (dispatch round N+1 before consuming
round N's fetch) and the segment diet (numpy slot-state mirrors, lazy
annotation, vectorized prof fold), steady-state host bookkeeping must
fit under device execution: wall/step ~ max(host, device), not host +
device. These tests pin that via the engine's own attribution plane
(telemetry/prof.py) so the host loop can't silently regrow.

Window mechanics follow tests/test_dispatch_budget.py: open the steady
window only after every slot is decoding, close it well before any
request finishes — admission/release patches and one-off XLA compiles
(both legitimately expensive) stay outside the measured window.
"""
import asyncio
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.telemetry.prof import SEGMENTS

PS = 16

# the dieted segments and their steady-state per-step ceilings (ms).
# Measured values on the tiny CPU harness sit at 0.002-0.02 ms/step;
# the ceilings leave ~10x headroom for shared-runner noise while still
# sitting far below the per-slot-Python-scan costs they replaced.
SEGMENT_CEILINGS_MS = {
    "intake": 0.25,        # queue-empty fast path
    "slot_scan": 0.25,     # numpy slot-state mirrors, no per-slot scan
    "seal_assembly": 0.25,  # preallocated batch packing
    "annotate": 0.25,      # lazy tuples, materialized only at finish
    "metrics_fold": 0.35,   # publish-cadence numpy fold
}


def _engine(**kw) -> TpuEngine:
    base = dict(
        num_pages=128, page_size=PS, max_pages_per_seq=16,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32",
    )
    base.update(kw)
    return TpuEngine(ModelConfig.tiny(dtype="float32"),
                     EngineConfig(**base),
                     mesh_config=MeshConfig(tp=1))


async def _steady_window(eng, n_req=4, osl=64):
    """Run n_req concurrent decodes and return (prof segment deltas in
    seconds, steps) over the steady all-slots-decoding window."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, 48).tolist() for _ in range(n_req)]
    progress = [0] * n_req

    async def one(i):
        async for out in eng.generate(PreprocessedRequest(
            token_ids=list(prompts[i]),
            stop_conditions=StopConditions(max_tokens=osl,
                                           ignore_eos=True),
        )):
            progress[i] += len(out.token_ids)

    tasks = [asyncio.ensure_future(one(i)) for i in range(n_req)]
    while not all(p >= 4 for p in progress):
        await asyncio.sleep(0.005)
    p0 = eng.prof.totals()
    s0 = eng.step_count
    t0 = time.monotonic()
    # close 20 tokens short of osl: the dispatch front leads emission by
    # the pipeline lag, so release patches stay out of the window
    while not any(p >= osl - 20 for p in progress):
        await asyncio.sleep(0.005)
    wall = time.monotonic() - t0
    p1 = eng.prof.totals()
    steps = eng.step_count - s0
    await asyncio.gather(*tasks)
    segs = {
        s: p1["segments"][s] - p0["segments"][s] for s in SEGMENTS
    }
    return segs, steps, wall


def _device_ms_per_step(eng, osl, reps=10):
    """Blocking reps of the hot fused round at the engine's own state —
    the same device-only methodology as bench.py phase B and
    tools/profile_round.py --dispatch-budget. Call after eng.stop()
    (the loop must not patch _dev while the reps donate it)."""
    e = eng.ecfg
    B = e.max_decode_slots
    dev = dict(
        eng._dev,
        ctx=jnp.full((B,), 48 + osl, jnp.int32),
        dest=jnp.arange(B, dtype=jnp.int32),
        tokens=jnp.ones((B,), jnp.int32),
    )

    def one_round(dev):
        out = eng._engine_round_seal(
            eng.params, eng.ctx, eng.ring, dev, eng.cache,
            *eng._zero_seal, e.flush_every, False, False,
        )
        eng.ctx, eng.ring, eng.cache = out[0], out[1], out[3]
        jax.block_until_ready(out)
        return out[2]

    # two warmups: the first call's outputs carry jit-output shardings
    # that key one more compilation
    dev = one_round(one_round(dev))
    t0 = time.monotonic()
    for _ in range(reps):
        dev = one_round(dev)
    return (time.monotonic() - t0) / (reps * e.flush_every) * 1e3


async def test_steady_host_fits_under_device():
    """THE pin: steady-decode host bookkeeping per step must not exceed
    device execution per step, i.e. the pipeline hides host work under
    the in-flight program. Same definition as bench.py phase B:
    host_ms_per_step := wall_ms_per_step - device_ms_per_step. (The
    prof segment sum is NOT usable as "host" here: in the pipelined
    regime the block-wait on the in-flight round lands in whichever
    segment touches the device first — fetch, or dispatch on backends
    that bound enqueue depth — so device time leaks into segments.)"""
    eng = _engine()
    eng.start()
    segs, steps, wall = await _steady_window(eng)
    await eng.stop()
    assert steps >= 16, steps
    wall_ms = wall / steps * 1e3
    device_ms = _device_ms_per_step(eng, osl=64)
    host_ms = wall_ms - device_ms
    assert host_ms <= device_ms, (
        f"host {host_ms:.4f} ms/step > device {device_ms:.4f} ms/step "
        f"(wall {wall_ms:.4f}); segment breakdown "
        f"{({s: round(v / steps * 1e3, 4) for s, v in segs.items()})}"
    )


async def test_dieted_segment_ceilings():
    """Per-segment ceilings on the segments this PR dieted: each must
    stay well under its pre-diet per-slot-Python-scan cost."""
    eng = _engine()
    eng.start()
    segs, steps, _ = await _steady_window(eng)
    await eng.stop()
    assert steps >= 16, steps
    per_step_ms = {s: v / steps * 1e3 for s, v in segs.items()}
    for seg, ceiling in SEGMENT_CEILINGS_MS.items():
        assert per_step_ms[seg] <= ceiling, (
            f"segment {seg!r} at {per_step_ms[seg]:.4f} ms/step exceeds "
            f"its {ceiling} ms ceiling; full breakdown "
            f"{({s: round(v, 4) for s, v in per_step_ms.items()})}"
        )


async def test_pipeline_engages_in_steady_decode():
    """The pipeline must actually run in steady state: early dispatches
    happen, measured depth > 1 (double-buffered), and some completion
    work is hidden under device execution."""
    eng = _engine()
    eng.start()
    await _steady_window(eng)
    stats = eng.pipeline_stats()
    await eng.stop()
    assert stats["round_pipeline"] is True
    assert stats["pipelined_dispatches"] >= 8, stats
    assert stats["pipeline_depth"] > 1.0, stats
    assert 0.0 < stats["overlap_ratio"] <= 1.0, stats


async def test_pipeline_off_is_serialized():
    """--round-pipeline off: the legacy order, no early dispatches."""
    eng = _engine(round_pipeline=False)
    eng.start()
    segs, steps, _ = await _steady_window(eng)
    stats = eng.pipeline_stats()
    await eng.stop()
    assert steps >= 16, steps
    assert stats["round_pipeline"] is False
    assert stats["pipelined_dispatches"] == 0, stats
    assert stats["pipeline_depth"] == 0.0, stats
