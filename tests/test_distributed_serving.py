"""Full-system distributed serving test (reference: the examples/llm graph
run against mock workers + docker-compose etcd/NATS, SURVEY.md §4.5/4.7).

store <- workers (register_llm + KV events)  <- discovery -> frontend
HTTP requests stream through the KV router to mocker workers; killing a
worker fails over; KV events concentrate prefix traffic.
"""
import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.frontend import HttpService, ModelManager
from dynamo_tpu.frontend.watcher import ModelEntry, ModelWatcher, register_llm
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import serve_store

BS = 4


async def setup_system(n_workers=2):
    server, store, port = None, None, None
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]

    workers = []
    for i in range(n_workers):
        rt = await DistributedRuntime.connect(port=port)
        eng = MockerEngine(
            MockerArgs(speedup_ratio=100.0, page_size=BS, num_pages=64)
        )
        entry = ModelEntry(
            name="mock-model", namespace="test", component="backend",
            block_size=BS, router_mode="kv",
        )
        served = await register_llm(rt, eng, entry, lease_ttl_s=0.4)
        workers.append((rt, eng, served))

    frontend_rt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    watcher = await ModelWatcher(
        frontend_rt, manager, namespace="test",
        router_config=KvRouterConfig(router_temperature=0.0),
    ).start()
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    return server, workers, frontend_rt, watcher, client, manager


async def teardown(server, workers, frontend_rt, watcher, client):
    await client.close()
    await watcher.stop()
    await frontend_rt.close()
    for rt, eng, served in workers:
        await served.shutdown()
        await eng.stop()
        await rt.close()
    server.close()


async def chat(client, content, max_tokens=4):
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "mock-model",
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens,
        },
    )
    return r


async def test_discovery_serving_and_failover():
    server, workers, frontend_rt, watcher, client, manager = await setup_system(2)
    try:
        # model discovered from worker registration
        for _ in range(100):
            if len(manager) > 0:
                break
            await asyncio.sleep(0.02)
        assert manager.list_models() == ["mock-model"]

        r = await chat(client, "w1 w2 w3 w4 w5")
        assert r.status == 200
        body = await r.json()
        assert body["choices"][0]["finish_reason"] in ("stop", "length")

        # kill worker 0 ungracefully: cancel its keep-alive (lease expires)
        rt0, eng0, served0 = workers[0]
        served0.lease._task.cancel()
        await served0.server.stop()
        # traffic must keep working throughout failover
        deadline = asyncio.get_running_loop().time() + 6
        ok = 0
        while asyncio.get_running_loop().time() < deadline:
            r = await chat(client, "w1 w2 w3 w4 w5")
            if r.status == 200:
                ok += 1
            await asyncio.sleep(0.05)
            routers = watcher._routers
            if routers and len(routers["mock-model"].workers) == 1:
                break
        assert ok > 0
        # after eviction, requests consistently succeed on the survivor
        for _ in range(3):
            r = await chat(client, "w6 w7")
            assert r.status == 200
    finally:
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_kv_events_flow_to_router_and_concentrate():
    server, workers, frontend_rt, watcher, client, manager = await setup_system(3)
    try:
        for _ in range(100):
            if len(manager) > 0:
                break
            await asyncio.sleep(0.02)
        push = None
        for _ in range(100):
            push = watcher._routers.get("mock-model")
            if push is not None and len(push.workers) == 3:
                break
            await asyncio.sleep(0.02)
        assert push is not None and len(push.workers) == 3

        prefix = " ".join(f"w{i%9}" for i in range(40))
        await chat(client, prefix)
        # events propagate asynchronously over pub/sub; wait until the
        # stream settles so the warm worker's full prefix is indexed
        last = -1
        for _ in range(100):
            n = push.router.indexer.events_applied
            if n > 0 and n == last:
                break
            last = n
            await asyncio.sleep(0.05)
        assert push.router.indexer.events_applied > 0

        # follow-ups with the same prefix concentrate on the warm worker
        before = {w: e.tokens_generated for (_, e, _), w in zip(
            workers, [str(s.lease_id) for _, _, s in workers]
        )}
        hits = {w: 0 for w in before}
        for _ in range(6):
            await chat(client, prefix)
            for (_, e, s) in workers:
                w = str(s.lease_id)
                if e.tokens_generated > before[w]:
                    hits[w] += 1
                before[w] = e.tokens_generated
        assert max(hits.values()) == 6, hits
    finally:
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_models_sharing_component_do_not_cross_route():
    """Two models registered on the SAME component/endpoint must each route
    only to their own workers (instances are tagged with their model)."""
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]

    engines = {}
    workers = []
    for name in ("ma", "mb"):
        rt = await DistributedRuntime.connect(port=port)
        eng = MockerEngine(
            MockerArgs(speedup_ratio=100.0, page_size=BS, num_pages=64)
        )
        engines[name] = eng
        entry = ModelEntry(name=name, namespace="test", component="backend",
                           block_size=BS, router_mode="kv")
        served = await register_llm(rt, eng, entry, lease_ttl_s=0.4)
        workers.append((rt, eng, served))

    frontend_rt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, namespace="test").start()
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    try:
        for _ in range(100):
            if len(manager) == 2:
                break
            await asyncio.sleep(0.02)
        assert manager.list_models() == ["ma", "mb"]
        for name in ("ma", "mb"):
            r = await client.post(
                "/v1/chat/completions",
                json={"model": name,
                      "messages": [{"role": "user", "content": "w1 w2 w3"}],
                      "max_tokens": 4},
            )
            assert r.status == 200
        # each mocker served exactly its own model's request
        assert engines["ma"].tokens_generated == 4
        assert engines["mb"].tokens_generated == 4
    finally:
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_tpu_engine_through_distributed_stack():
    """VERDICT r2 weak #6: a REAL TpuEngine registered via register_llm on
    CPU, with KV events flowing from the engine thread through the
    (thread-safe) publisher into the frontend router's indexer."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig

    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    cfg = ModelConfig.tiny(dtype="float32")
    eng = TpuEngine(
        cfg,
        EngineConfig(num_pages=32, page_size=4, max_pages_per_seq=16,
                     max_decode_slots=2, prefill_buckets=(32, 64),
                     cache_dtype="float32"),
        params=llama.init_params(cfg, 0),
        mesh_config=MeshConfig(tp=1),
    )
    entry = ModelEntry(name="tpum", namespace="tt", component="backend",
                       block_size=4, router_mode="kv")
    served = await register_llm(rt, eng, entry, lease_ttl_s=0.5)

    frontend_rt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, namespace="tt").start()
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    try:
        for _ in range(100):
            if len(manager) > 0:
                break
            await asyncio.sleep(0.05)
        r = await client.post("/v1/chat/completions", json={
            "model": "tpum",
            "messages": [{"role": "user", "content": "w1 w2 w3 w4 w5 w6"}],
            "max_tokens": 8,
        })
        assert r.status == 200
        assert (await r.json())["usage"]["completion_tokens"] >= 1

        # KV events produced by the ENGINE THREAD reached the frontend
        # router's indexer via the store pub/sub plane
        router = watcher._routers["tpum"]
        for _ in range(100):
            if router.router.indexer.total_blocks() > 0:
                break
            await asyncio.sleep(0.05)
        assert router.router.indexer.total_blocks() > 0
    finally:
        await client.close()
        await watcher.stop()
        await frontend_rt.close()
        await served.shutdown()
        await eng.stop()
        await rt.close()
        server.close()


async def test_kv_events_claimed_per_model_with_race_buffer():
    """VERDICT r2 weak #5: KV events go only to the router that owns the
    worker; events racing discovery wait in the buffer and replay."""
    import json as _json

    from dynamo_tpu.kv_router.protocols import KvCacheEvent, KvEventKind, StoredBlock

    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]

    frontend_rt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, namespace="cm").start()

    workers = []
    engines = {}
    for name in ("ma", "mb"):
        rt = await DistributedRuntime.connect(port=port)
        eng = MockerEngine(
            MockerArgs(speedup_ratio=100.0, page_size=BS, num_pages=64)
        )
        engines[name] = eng
        served = await register_llm(
            rt, eng,
            ModelEntry(name=name, namespace="cm", component="backend",
                       block_size=BS, router_mode="kv"),
            lease_ttl_s=0.5,
        )
        workers.append((rt, eng, served))
    try:
        for _ in range(100):
            if len(manager) == 2:
                break
            await asyncio.sleep(0.05)
        wid_a = str(workers[0][2].lease_id)

        # publish an event from ma's worker: only ma's indexer gets it
        pub_rt = await DistributedRuntime.connect(port=port)
        ev = KvCacheEvent(kind=KvEventKind.STORED, worker_id=wid_a,
                          parent_hash=0,
                          blocks=[StoredBlock(block_hash=777)])
        await pub_rt.kv.publish(
            f"kv_events.{wid_a}", _json.dumps(ev.to_dict())
        )
        for _ in range(100):
            if watcher._routers["ma"].router.indexer.total_blocks():
                break
            await asyncio.sleep(0.05)
        assert watcher._routers["ma"].router.indexer.total_blocks() == 1
        assert watcher._routers["mb"].router.indexer.total_blocks() == 0

        # an event for an UNKNOWN worker is buffered, not lost: when the
        # worker registers for mb, the event replays into mb's indexer
        ev2 = KvCacheEvent(kind=KvEventKind.STORED, worker_id="future-w",
                           parent_hash=0,
                           blocks=[StoredBlock(block_hash=888)])
        await pub_rt.kv.publish("kv_events.future-w",
                                _json.dumps(ev2.to_dict()))
        await asyncio.sleep(0.3)
        assert len(watcher._unclaimed_events) == 1
        # simulate the worker appearing in mb's router
        watcher._routers["mb"].add_worker("future-w", engines["mb"])
        watcher._replay_unclaimed()
        assert watcher._routers["mb"].router.indexer.total_blocks() == 1
        assert not watcher._unclaimed_events
        await pub_rt.close()
    finally:
        await watcher.stop()
        await frontend_rt.close()
        for rt, eng, served in workers:
            await served.shutdown()
            await eng.stop()
            await rt.close()
        server.close()
