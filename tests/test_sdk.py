"""SDK decorator surface (reference deploy/sdk core/lib.py:88,121 +
protocol/deployment.py): @service/@endpoint/@depends author a graph; the
same declaration serves in-process over the runtime, builds the
supervisor graph dict, and deploys to the operator's store key."""
import json

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import serve_store
from dynamo_tpu.sdk import build, deploy, depends, endpoint, serve_graph, service


@service(namespace="sdkt", replicas=2, tpu_chips=4,
         args=["out=tpu", "--model-config", "llama3_1b"])
class Backend:
    @endpoint()
    async def generate(self, payload):
        for t in payload.get("token_ids", []):
            yield {"tok": t * 2}


@service(namespace="sdkt")
class Api:
    backend = depends(Backend)

    @endpoint()
    async def chat(self, payload):
        async for out in self.backend.generate(payload):
            yield {"chat": out["tok"]}


def test_decorators_collect_metadata():
    meta = Backend._dynamo_service
    assert meta.name == "backend" and meta.replicas == 2
    assert meta.endpoints == {"generate": "generate"}
    assert Api._dynamo_service.dependencies["backend"] is Backend


def test_endpoint_must_be_async_generator():
    with pytest.raises(TypeError, match="async generator"):
        @service()
        class Bad:
            @endpoint()
            async def f(self, payload):
                return payload


def test_depends_requires_service():
    with pytest.raises(TypeError, match="not a @service"):
        depends(dict)


async def test_serve_graph_end_to_end():
    """Both services live on a real runtime; the Api's depends() proxy
    routes through discovery + push RPC, not a direct reference."""
    server, _ = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    rt_s = await DistributedRuntime.connect(port=port)
    rt_c = await DistributedRuntime.connect(port=port)
    graph = await serve_graph(rt_s, Backend, Api)
    try:
        client = await rt_c.namespace("sdkt").component("api").endpoint(
            "chat").client()
        got = []
        async for item in client.generate({"token_ids": [1, 2, 3]}):
            got.append(item["chat"])
        assert got == [2, 4, 6]
    finally:
        await graph.stop()
        await rt_c.close()
        await rt_s.close()
        server.close()


async def test_build_and_deploy():
    g = build(Backend, Api, http_port=9090)
    assert g["namespace"] == "sdkt"
    assert g["frontend"]["http_port"] == 9090
    names = {w["name"]: w for w in g["workers"]}
    assert names["backend"]["replicas"] == 2
    assert names["backend"]["tpu_chips"] == 4
    assert "api" in names

    # the built graph renders to k8s objects (operator compatibility)
    from dynamo_tpu.k8s import emit_k8s_manifests, graph_key

    objs = emit_k8s_manifests(g)
    assert any(o["metadata"]["name"] == "sdkt-backend" for o in objs)

    # deploy writes the operator's spec key
    from dynamo_tpu.runtime.client import KvClient

    server, _ = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    try:
        key = await deploy(kv, Backend, Api)
        assert key == graph_key("sdkt")
        assert json.loads(await kv.get(key))["workers"]
    finally:
        await kv.close()
        server.close()
