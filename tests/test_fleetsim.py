"""Fleet flight simulator tests (ISSUE 16 tentpole + satellites).

Covers: seeded trace generators are replay-identical (+ JSONL round
trip), virtual clock invariants (monotonicity, compression, sleep
advance), SimConnector scale-up/drain against a LIVE store, the
predictive-vs-reactive planner differential on a synthetic rising wave,
WAL fsync batching, calibrate_mocker inversion, and a ~32-worker
fleet_sim smoke through the real store/watcher/router planes.
"""
import asyncio
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from calibrate_mocker import mocker_args_from_profile  # noqa: E402

from dynamo_tpu.fleetsim.clock import REAL_CLOCK, Clock, VirtualClock
from dynamo_tpu.fleetsim.sim import SimConnector, SimFleet
from dynamo_tpu.fleetsim.traces import (
    PromptPopulation,
    TraceRequest,
    diurnal_trace,
    load_jsonl,
    mmpp_trace,
    save_jsonl,
)
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.planner import Planner, PlannerConfig
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import KvStore, serve_store


# ---------------------------------------------------------------- clock


def test_real_clock_is_default_and_passthrough():
    assert REAL_CLOCK.rate == 1.0
    before = time.monotonic()
    mid = REAL_CLOCK.monotonic()
    after = time.monotonic()
    assert before <= mid <= after
    assert REAL_CLOCK.to_wall(7.5) == 7.5


def test_virtual_clock_rejects_nonpositive_rate():
    with pytest.raises(ValueError):
        VirtualClock(rate=0)
    with pytest.raises(ValueError):
        VirtualClock(rate=-3)


def test_virtual_clock_monotonic_never_regresses():
    clk = VirtualClock(rate=50.0)
    prev = clk.monotonic()
    for _ in range(200):
        cur = clk.monotonic()
        assert cur >= prev
        prev = cur


async def test_virtual_clock_compression():
    clk = VirtualClock(rate=40.0)
    v0, w0 = clk.monotonic(), time.monotonic()
    await clk.sleep(2.0)  # 2 virtual seconds = 50ms wall
    v1, w1 = clk.monotonic(), time.monotonic()
    assert v1 - v0 >= 2.0                 # virtual time advanced by >= v
    assert w1 - w0 < 1.0                  # ...in far less wall time
    assert clk.to_wall(40.0) == pytest.approx(1.0)


def test_clock_subclass_contract():
    # components accept any Clock; a trivial override must satisfy the
    # same surface REAL_CLOCK does
    class Frozen(Clock):
        def monotonic(self):
            return 123.0

    assert Frozen().monotonic() == 123.0
    assert Frozen().to_wall(5.0) == 5.0


# --------------------------------------------------------------- traces


def test_trace_generators_replay_identical():
    for gen in (
        lambda s: diurnal_trace(60, 1.0, 6.0, 40.0, seed=s),
        lambda s: mmpp_trace(60, 1.0, 8.0, seed=s),
    ):
        a, b = gen(5), gen(5)
        assert [r.__dict__ for r in a] == [r.__dict__ for r in b]
        c = gen(6)
        assert [r.__dict__ for r in a] != [r.__dict__ for r in c]


def test_trace_arrivals_sorted_and_bounded():
    trace = mmpp_trace(30, 2.0, 10.0, seed=3)
    arr = [r.arrival_s for r in trace]
    assert arr == sorted(arr)
    assert all(0 <= t < 30 for t in arr)
    ids = [r.request_id for r in trace]
    assert len(set(ids)) == len(ids)


def test_prompt_population_shares_prefixes():
    import random

    pop = PromptPopulation(n_prefixes=4, prefix_len=32, suffix_len=8,
                           seed=1)
    rng = random.Random(2)
    prompts = [pop.sample(rng) for _ in range(64)]
    assert all(len(p) == 40 for p in prompts)
    heads = {tuple(p[:32]) for p in prompts}
    # Zipf-hot prefixes: far fewer distinct heads than prompts
    assert len(heads) <= 4
    tails = {tuple(p[32:]) for p in prompts}
    assert len(tails) > len(heads)


def test_trace_jsonl_round_trip(tmp_path):
    trace = diurnal_trace(20, 1.0, 4.0, 15.0, seed=9)
    p = str(tmp_path / "trace.jsonl")
    save_jsonl(p, trace)
    back = load_jsonl(p)
    assert [r.__dict__ for r in back] == [r.__dict__ for r in trace]
    assert isinstance(back[0], TraceRequest)


# ----------------------------------------------------- WAL fsync batching


def test_store_rejects_unknown_fsync_mode():
    with pytest.raises(ValueError):
        KvStore(fsync_mode="sometimes")


def test_wal_batch_mode_coalesces_and_survives_restart(tmp_path):
    from dynamo_tpu.runtime.store_metrics import STORE

    journal = str(tmp_path / "wal.jsonl")
    before = STORE.get("dynamo_store_wal_batched_syncs_total")
    s = KvStore(journal_path=journal, fsync_mode="batch")
    # no running loop here: batch mode degrades to immediate synced
    # writes, so durability never regresses below `always`
    s.put("a", "1")
    s.put("b", "2")
    lease = s.lease_grant(30.0)
    s.put("c", "3", lease=lease)
    s.close_journal()
    assert STORE.get("dynamo_store_wal_batched_syncs_total") > before

    s2 = KvStore(journal_path=journal, fsync_mode="batch")
    assert s2.get("a")[0] == "1"
    assert s2.get("b")[0] == "2"
    assert s2.get("c")[0] == "3"
    s2.close_journal()


async def test_wal_batch_mode_one_fsync_per_drain(tmp_path):
    from dynamo_tpu.runtime.store_metrics import STORE

    journal = str(tmp_path / "wal.jsonl")
    s = KvStore(journal_path=journal, fsync_mode="batch")
    before = STORE.get("dynamo_store_wal_batched_syncs_total")
    # a burst of mutations inside one event-loop drain...
    for i in range(32):
        s.put(f"k{i}", str(i))
    assert s._wal_pending  # buffered, not yet flushed
    await asyncio.sleep(0)  # let the scheduled drain run
    after = STORE.get("dynamo_store_wal_batched_syncs_total")
    assert after == before + 1  # ...coalesced into ONE flush+fsync
    assert not s._wal_pending
    s.close_journal()
    s2 = KvStore(journal_path=journal)
    assert s2.get("k31")[0] == "31"
    s2.close_journal()


def test_wal_always_mode_unchanged(tmp_path):
    journal = str(tmp_path / "wal.jsonl")
    s = KvStore(journal_path=journal)
    assert s.fsync_mode == "always"
    s.put("x", "y")
    # always mode never buffers: the record is on disk before put returns
    assert not s._wal_pending
    with open(journal) as f:
        assert any('"x"' in line for line in f)
    s.close_journal()


# ----------------------------------------------- calibrate_mocker (tool)


def _profile(ttft=0.128, itl=0.02, isl=64, slots=8):
    return {
        "isl": isl, "osl": 32,
        "configs": [{
            "name": "cfg-a",
            "config": {"max_decode_slots": slots},
            "points": [
                {"concurrency": 1, "ttft_p50_s": ttft, "ttft_p99_s": ttft,
                 "itl_p50_s": itl, "itl_p99_s": itl, "tok_s": 100.0},
                {"concurrency": 4, "ttft_p50_s": ttft * 3,
                 "ttft_p99_s": ttft * 4, "itl_p50_s": itl * 2,
                 "itl_p99_s": itl * 3, "tok_s": 300.0},
            ],
        }],
    }


def test_calibrate_mocker_inverts_concurrency_one_point():
    out = mocker_args_from_profile(_profile())
    assert out["prefill_time_per_token_s"] == pytest.approx(0.128 / 64)
    assert out["decode_time_per_step_s"] == pytest.approx(0.02)
    assert out["max_decode_slots"] == 8


def test_calibrate_mocker_config_selection_and_errors():
    prof = _profile()
    assert mocker_args_from_profile(prof, config_name="cfg-a")
    with pytest.raises(ValueError):
        mocker_args_from_profile(prof, config_name="nope")
    with pytest.raises(ValueError):
        mocker_args_from_profile({"isl": 0, "configs": []})
    with pytest.raises(ValueError):
        mocker_args_from_profile(_profile(ttft=0.0))


def test_calibrate_mocker_cli(tmp_path):
    from calibrate_mocker import main as cal_main

    prof_path = str(tmp_path / "prof.json")
    out_path = str(tmp_path / "args.json")
    with open(prof_path, "w") as f:
        json.dump(_profile(), f)
    assert cal_main([prof_path, "-o", out_path]) == 0
    with open(out_path) as f:
        out = json.load(f)
    assert out["decode_time_per_step_s"] == pytest.approx(0.02)


# ------------------------------- planner: predictive vs reactive (unit)


class FakeConnector:
    def __init__(self, n: int = 1):
        self.n = n
        self.calls: list[int] = []

    def current_replicas(self) -> int:
        return self.n

    async def set_replicas(self, n: int) -> None:
        self.calls.append(n)
        self.n = n


def _streams_metrics(worker, active, waiting=0):
    return ForwardPassMetrics(
        worker_id=worker,
        worker_stats=WorkerStats(request_active_slots=active,
                                 num_requests_waiting=waiting),
        kv_stats=KvStats(gpu_cache_usage_perc=0.5),
    )


def _make_planner(predictor, conn):
    return Planner(
        kv=None, connector=conn,
        config=PlannerConfig(
            min_replicas=2, max_replicas=12, stable_intervals=3,
            predictor=predictor, predictive=True,
            streams_per_replica=4.0,
        ),
    )


def test_predictive_scales_ahead_of_rising_wave():
    """Feed both arms the same synthetic rising stream counts; the AR
    arm's target must exceed the constant (reactive) arm's BEFORE the
    wave peaks — that is the whole point of predictive mode."""
    wave = [4, 8, 12, 16, 20, 24, 28]  # rising, peaks later at 40
    targets = {}
    for predictor in ("constant", "ar"):
        conn = FakeConnector(2)
        planner = _make_planner(predictor, conn)
        seq = []
        for streams in wave:
            planner.aggregator._latest.clear()
            planner.aggregator.update(
                _streams_metrics("w0", active=streams))
            seq.append(planner.decide())
        targets[predictor] = seq
    # reactive sizes for the CURRENT count: last point 28/4 = 7
    assert targets["constant"][-1] == 7
    # predictive extrapolates the +4/interval trend: 32/4 = 8
    # (earlier points run on the AR warm-up mean fallback, which trails a
    # rising series — only the fitted tail demonstrates look-ahead)
    assert targets["ar"][-1] > targets["constant"][-1]


def test_predictive_inert_without_capacity():
    conn = FakeConnector(2)
    planner = Planner(
        kv=None, connector=conn,
        config=PlannerConfig(min_replicas=1, max_replicas=8,
                             predictor="ar", predictive=True,
                             streams_per_replica=0.0),
    )
    planner.aggregator.update(_streams_metrics("w0", active=30))
    # no capacity model -> the predictive floor cannot fire; thresholds
    # alone decide (usage 0.5 is in-band, waiting 0 -> hold)
    assert planner.decide() == 2


async def test_planner_adjust_emits_metrics():
    from dynamo_tpu.planner_metrics import PLANNER

    conn = FakeConnector(2)
    planner = _make_planner("constant", conn)
    planner.aggregator.update(_streams_metrics("w0", active=24))
    before = PLANNER.get("dynamo_planner_scale_ups_total")
    decisions_before = PLANNER.get("dynamo_planner_decisions_total")
    target = await planner.adjust()
    assert target == 6
    assert conn.calls == [6]
    assert PLANNER.get("dynamo_planner_replicas") == 6
    assert PLANNER.get("dynamo_planner_decisions_total") \
        == decisions_before + 1
    assert PLANNER.get("dynamo_planner_scale_ups_total") == before + 1
    assert PLANNER.get("dynamo_planner_predicted_load") == 24


def test_queue_wait_trigger_scales_up():
    from dynamo_tpu.overload.load import WorkerLoadView

    class FakeView:
        def est_wait_s(self, wid):
            return 9.0

    conn = FakeConnector(2)
    planner = Planner(
        kv=None, connector=conn,
        config=PlannerConfig(min_replicas=1, max_replicas=8,
                             queue_wait_scale_up_s=2.0),
        load_view=FakeView(),
    )
    planner.aggregator.update(_streams_metrics("w0", active=1))
    assert planner.decide() == 3  # +1 despite in-band usage/waiting
    assert isinstance(WorkerLoadView(), WorkerLoadView)  # import sanity


# ------------------------------------ sim fleet against a live store


async def _discover(watcher, name, n, tries=400):
    push = None
    for _ in range(tries):
        push = watcher._routers.get(name)
        if push is not None and len(push.workers) >= n:
            return push
        await asyncio.sleep(0.02)
    raise AssertionError(
        f"fleet never discovered ({0 if push is None else len(push.workers)}"
        f"/{n})")


def _sim_stack(port, namespace, clock=REAL_CLOCK):
    from dynamo_tpu.frontend.watcher import ModelEntry
    from dynamo_tpu.mocker import MockerArgs

    entry = ModelEntry(name="sim-model", namespace=namespace,
                       component="backend", block_size=16,
                       router_mode="kv")

    def make_args(idx):
        return MockerArgs(num_pages=64, page_size=16, max_decode_slots=4,
                          prefill_time_per_token_s=1e-5,
                          decode_time_per_step_s=1e-4)

    return entry, make_args


async def test_sim_connector_scales_and_drains_live_store():
    from dynamo_tpu.frontend import ModelManager
    from dynamo_tpu.frontend.watcher import ModelWatcher
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    server, store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    entry, make_args = _sim_stack(port, "fleetsim_test")
    fleet = SimFleet(rt, entry, make_args, lease_ttl_s=30.0,
                     metrics_interval_s=5.0)
    frontend_rt = await DistributedRuntime.connect(port=port)
    watcher = await ModelWatcher(
        frontend_rt, ModelManager(), namespace="fleetsim_test",
        router_config=KvRouterConfig(router_temperature=0.0),
        engine_factory=fleet.engine_factory,
    ).start()
    conn = SimConnector(fleet)
    try:
        await conn.set_replicas(4)
        assert conn.current_replicas() == 4
        # registrations are REAL: leased instance keys live in the store
        prefix = "dynamo://fleetsim_test/_components/backend/generate/"
        assert len(store.get_prefix(prefix)) == 4
        push = await _discover(watcher, "sim-model", 4)

        # scale down: newest-first drain revokes leases -> keys vanish
        await conn.set_replicas(1)
        assert conn.current_replicas() == 1
        assert len(store.get_prefix(prefix)) == 1
        for _ in range(200):
            if len(push.workers) == 1:
                break
            await asyncio.sleep(0.02)
        assert len(push.workers) == 1
        assert conn.calls == [4, 1]
    finally:
        await watcher.stop()
        await fleet.stop()
        await frontend_rt.close()
        await rt.close()
        server.close()


async def test_fleet_sim_smoke_32_workers():
    """Tier-1 smoke: 32 in-process workers register against a live
    batch-fsync store, the watcher discovers them all, and a burst of
    requests routes through the real KvPushRouter with zero failures."""
    import tempfile

    from dynamo_tpu.frontend import ModelManager
    from dynamo_tpu.frontend.watcher import ModelWatcher
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )

    n = 32
    tmp = tempfile.mkdtemp(prefix="fleetsim-smoke-")
    server, store = await serve_store(
        port=0, sweep_interval_s=0.5,
        journal_path=f"{tmp}/wal.jsonl", fsync_mode="batch")
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    entry, make_args = _sim_stack(port, "fleetsim_smoke")
    entry.namespace = "fleetsim_smoke"
    fleet = SimFleet(rt, entry, make_args, lease_ttl_s=30.0,
                     metrics_interval_s=5.0)
    frontend_rt = await DistributedRuntime.connect(port=port)
    watcher = await ModelWatcher(
        frontend_rt, ModelManager(), namespace="fleetsim_smoke",
        router_config=KvRouterConfig(router_temperature=0.0),
        engine_factory=fleet.engine_factory,
    ).start()
    try:
        rev0 = store.revision
        await fleet.scale_to(n)
        assert store.revision > rev0
        push = await _discover(watcher, "sim-model", n)

        decisions = []
        push.on_decision = decisions.append
        trace = mmpp_trace(5.0, 4.0, 16.0, seed=2, max_tokens=4,
                           population=PromptPopulation(
                               n_prefixes=4, prefix_len=32, suffix_len=8,
                               seed=2))
        failed = 0

        async def one(tr):
            nonlocal failed
            req = PreprocessedRequest(
                token_ids=list(tr.token_ids),
                stop_conditions=StopConditions(max_tokens=tr.max_tokens,
                                               ignore_eos=True))
            # dynlint: disable=DTL007 — the smoke counts failures
            try:
                async for _ in push.generate(req):
                    pass
            except Exception:  # noqa: BLE001 — counted, asserted zero
                failed += 1

        await asyncio.gather(*[one(tr) for tr in trace])
        assert failed == 0
        assert len(push.workers) == n
        assert decisions and all(d >= 0 for d in decisions)
    finally:
        await watcher.stop()
        await fleet.stop()
        await frontend_rt.close()
        await rt.close()
        server.close()
        store.close_journal()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


async def test_mocker_on_virtual_clock_compresses_decode():
    """A mocker generating on a 50x clock finishes a stream whose
    simulated decode time is ~1.6 virtual seconds in well under that
    wall time — and the token stream is identical to a real-clock run."""
    from dynamo_tpu.mocker import MockerArgs, MockerEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )

    def make(clock=None):
        return MockerEngine(MockerArgs(
            num_pages=64, page_size=16, max_decode_slots=4,
            prefill_time_per_token_s=0.001,
            decode_time_per_step_s=0.1,
        ), clock=clock)

    def req():
        return PreprocessedRequest(
            token_ids=list(range(1, 33)),
            stop_conditions=StopConditions(max_tokens=16,
                                           ignore_eos=True))

    async def run(eng):
        toks = []
        async for out in eng.generate(req()):
            toks.extend(out.token_ids)
        await eng.stop()
        return toks

    vclock = VirtualClock(rate=50.0)
    t0 = time.monotonic()
    fast = await run(make(clock=vclock))
    fast_wall = time.monotonic() - t0
    assert fast_wall < 1.0  # 1.6+ virtual seconds compressed ~50x
    slow = await run(make())  # real clock default
    assert fast == slow  # determinism: clock changes timing, not tokens
