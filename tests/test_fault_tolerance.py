"""Fault-injection grid (reference tests/fault_tolerance/scenarios.py:140-207):
run the distributed serving graph under concurrent load, kill one component
mid-stream — {decode worker, frontend, store} on the aggregated config,
{prefill worker} on the disaggregated config — and assert post-failure
success rates. CPU-only via mocker / tiny TPU engines.
"""
import asyncio
from dataclasses import replace

import pytest

from tests.test_distributed_serving import chat, setup_system, teardown


async def _load_phase(client, n, content="w1 w2 w3 w4 w5"):
    """n sequential requests; returns #successes (sequential keeps the
    single-core CPU box deterministic under test)."""
    ok = 0
    for _ in range(n):
        try:
            r = await asyncio.wait_for(chat(client, content), timeout=10)
            if r.status == 200:
                ok += 1
        except (asyncio.TimeoutError, OSError):
            pass
        await asyncio.sleep(0.02)
    return ok


@pytest.mark.parametrize("victim", ["decode_worker", "frontend", "store"])
async def test_agg_kill_grid(victim):
    """Aggregated config: kill one component at t, measure success
    before/after (scenarios.py kill-at-30s grid, compressed)."""
    server, workers, frontend_rt, watcher, client, manager = (
        await setup_system(2)
    )
    try:
        for _ in range(100):
            if len(manager) > 0:
                break
            await asyncio.sleep(0.02)

        before = await _load_phase(client, 4)
        assert before == 4, "all pre-failure requests must succeed"

        if victim == "decode_worker":
            # ungraceful worker death: lease expires, router fails over
            rt0, eng0, served0 = workers[0]
            served0.lease._task.cancel()
            await served0.server.stop()
            # keep load flowing through the failover window
            deadline = asyncio.get_running_loop().time() + 6
            ok_during = 0
            while asyncio.get_running_loop().time() < deadline:
                r = await chat(client, "w1 w2 w3 w4 w5")
                if r.status == 200:
                    ok_during += 1
                routers = watcher._routers
                if routers and len(routers["mock-model"].workers) == 1:
                    break
                await asyncio.sleep(0.05)
            assert ok_during > 0, "traffic must survive the failover window"
            after = await _load_phase(client, 4)
            assert after == 4, "post-eviction traffic must fully recover"

        elif victim == "frontend":
            # frontend process death: a NEW frontend against the same store
            # rediscovers the fleet and serves (stateless-frontend contract)
            from aiohttp.test_utils import TestClient, TestServer

            from dynamo_tpu.frontend import HttpService, ModelManager
            from dynamo_tpu.frontend.watcher import ModelWatcher
            from dynamo_tpu.runtime.component import DistributedRuntime

            await client.close()
            await watcher.stop()
            await frontend_rt.close()

            port = server.sockets[0].getsockname()[1]
            frontend_rt = await DistributedRuntime.connect(port=port)
            manager2 = ModelManager()
            watcher = await ModelWatcher(
                frontend_rt, manager2, namespace="test"
            ).start()
            svc = HttpService(manager2)
            client = TestClient(TestServer(svc.app))
            await client.start_server()
            for _ in range(200):
                if len(manager2) > 0:
                    break
                await asyncio.sleep(0.02)
            after = await _load_phase(client, 4)
            assert after == 4, "replacement frontend must serve the fleet"

        else:  # store
            # control-plane outage: discovered routes keep serving (the
            # data plane is direct worker connections, not store-mediated)
            server.close()
            await asyncio.sleep(0.2)
            after = await _load_phase(client, 4)
            assert after == 4, (
                "data plane must survive a control-plane outage"
            )
    finally:
        try:
            await teardown(server, workers, frontend_rt, watcher, client)
        except Exception:  # noqa: BLE001 — components already killed above
            pass


async def test_disagg_kill_prefill_worker_under_load():
    """Disaggregated config: the prefill worker dies holding jobs; decode
    requests fall back to local prefill after the timeout and ALL still
    complete (scenarios.py prefill-kill row; disagg.py expiry/fallback)."""
    from tests.test_disagg import (
        req_for,
        setup,  # noqa: F401 — fixture reuse via direct call below
    )
    from tests.test_disagg import mk_engine, setup_disagg_pair, start_rt
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.engine.config import EngineConfig

    PS = 16
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    params = llama.init_params(cfg, 0)
    triple = (cfg, ecfg, params)

    server, store, rt, port = await start_rt()
    # generous timeout pre-kill (first prefill compiles the model);
    # tightened after the kill so the fallback window stays test-sized
    decode, srv, conf, pworker, pre_eng = await setup_disagg_pair(
        triple, rt, prefill_timeout_s=30.0
    )

    async def one(base):
        toks = []
        async for out in decode.generate(req_for(list(range(base, base + 49)),
                                                 n_new=6)):
            toks.extend(out.token_ids)
        return len(toks)

    try:
        # pre-failure: remote prefill works
        assert await one(1) == 6
        assert decode.remote_prefills >= 1

        # kill the prefill worker (holding the queue consumer)
        await pworker.stop()
        await pre_eng.stop()
        decode.prefill_timeout_s = 1.5

        # post-failure load: every request must still complete via the
        # local-prefill fallback after the timeout
        results = await asyncio.gather(
            *[one(100 * i) for i in range(1, 4)]
        )
        assert all(n == 6 for n in results), results
        assert decode.remote_fallbacks >= 1
    finally:
        await srv.stop()
        await conf.stop()
        await decode.stop()
        await rt.close()
        server.close()
