"""Recorder record/replay tests (reference recorder.rs:447-511 round-trip
test strategy). Keystone: record the KV-event stream of a live mocker run,
replay it into a fresh indexer, and get IDENTICAL overlap scores."""
import asyncio
import os

from dynamo_tpu.kv_router.indexer import KvIndexer
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.recorder import KvRecorder, Recorder
from dynamo_tpu.tokens import compute_block_hashes

BS = 4


def test_recorder_rotation(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    rec = Recorder(path, max_lines=3, max_files=3)
    for i in range(8):
        rec.record({"i": i})
    rec.close()
    # 8 events, 3/file: current has 2 (6,7), .1 has 3 (3,4,5), .2 has (0,1,2)
    assert [e["i"] for _, e in Recorder.iter_events(path)] == [6, 7]
    assert [e["i"] for _, e in Recorder.iter_events(path + ".1")] == [3, 4, 5]
    assert [e["i"] for _, e in Recorder.iter_events(path + ".2")] == [0, 1, 2]


def test_recorder_skips_corrupt_lines(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    rec = Recorder(path)
    rec.record({"ok": 1})
    rec.close()
    with open(path, "a") as f:
        f.write("not json at all\n")
    rec2 = Recorder(path)
    rec2.record({"ok": 2})
    rec2.close()
    events = [e for _, e in Recorder.iter_events(path)]
    assert events == [{"ok": 1}, {"ok": 2}]


async def test_kv_record_replay_identical_scores(tmp_path):
    path = str(tmp_path / "kv.jsonl")
    recorder = KvRecorder(path)
    live = KvIndexer(BS)

    def tee(ev):
        recorder(ev)
        live.apply_event(ev)

    eng = MockerEngine(
        MockerArgs(speedup_ratio=100.0, page_size=BS, num_pages=32,
                   worker_id="w0"),
        on_kv_event=tee,
    )
    prompts = [
        list(range(1, 30)),
        list(range(1, 18)) + [99, 98],      # shared prefix, divergent tail
        list(range(50, 75)),
    ]
    for p in prompts:
        async for _ in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        )):
            pass
    await eng.stop()
    recorder.close()
    assert recorder.recorder.recorded > 0

    # replay into a FRESH indexer: identical overlap scores for any query
    replayed = KvIndexer(BS)
    n = KvRecorder.replay(path, replayed)
    assert n == recorder.recorder.recorded
    for p in prompts + [list(range(1, 12)), list(range(60, 80))]:
        hashes = compute_block_hashes(p, BS)
        assert replayed.find_matches(hashes).scores == \
            live.find_matches(hashes).scores
