"""Multimodal E/P/D graph tests (reference examples/multimodal:
encode_worker.py:148, 3-stage disaggregation): vision tower -> embedding
transfer over the runtime -> prefill consumes image embeddings -> decode
produces the caption."""
import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.vision import (
    VisionConfig,
    encode_image,
    init_vision_params,
)
from dynamo_tpu.multimodal import (
    EncodeWorker,
    MultimodalEngine,
    encode_image_payload,
)
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

PS = 16
IMG_TOK = 7   # placeholder token id used in prompts


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    vcfg = VisionConfig.tiny(out_hidden_size=cfg.hidden_size)
    vparams = init_vision_params(vcfg, 0)
    params = llama.init_params(cfg, 0)
    ecfg = EngineConfig(
        num_pages=32, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    return cfg, vcfg, params, vparams, ecfg


def image(seed):
    rng = np.random.RandomState(seed)
    return rng.rand(16, 16, 3).astype(np.float32)


def mm_prompt(vcfg):
    """Prompt: 4 text tokens, then num_patches image placeholders, then
    3 more text tokens. Returns (tokens, image_pos)."""
    n = vcfg.num_patches
    toks = [1, 2, 3, 4] + [IMG_TOK] * n + [5, 6, 8]
    return toks, 4


def mm_request(vcfg, img, n_new=6):
    toks, pos = mm_prompt(vcfg)
    return PreprocessedRequest(
        token_ids=toks,
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
        multimodal={"images": [dict(encode_image_payload(img), pos=pos)]},
    )


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def test_vision_encoder_shapes(setup):
    cfg, vcfg, _, vparams, _ = setup
    out = encode_image(vcfg, vparams, jnp.asarray(image(0)))
    assert out.shape == (vcfg.num_patches, cfg.hidden_size)
    assert np.isfinite(np.asarray(out)).all()
    # different images -> different embeddings
    out2 = encode_image(vcfg, vparams, jnp.asarray(image(1)))
    assert not np.allclose(np.asarray(out), np.asarray(out2))


async def test_multimodal_e2e_inprocess(setup):
    """image -> encode -> prefill(inject) -> decode, against a manual
    reference computed with llama.prefill + explicit embeds."""
    cfg, vcfg, params, vparams, ecfg = setup
    rt = None
    inner = TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))
    enc = EncodeWorker(rt, vcfg, vparams)
    eng = MultimodalEngine(inner, local_encoder=enc)

    img = image(0)
    out = await collect(eng, mm_request(vcfg, img))
    assert len(out) == 6
    assert eng.images_resolved == 1

    # manual reference: same embeds through the raw model
    emb = np.asarray(encode_image(vcfg, vparams, jnp.asarray(img)),
                     np.float32)
    toks, pos = mm_prompt(vcfg)
    T = 32
    padded = np.zeros(T, np.int32)
    padded[: len(toks)] = toks
    ov = np.zeros((T, cfg.hidden_size), np.float32)
    msk = np.zeros(T, bool)
    ov[pos: pos + len(emb)] = emb
    msk[pos: pos + len(emb)] = True
    ctx = llama.init_ctx(cfg, 1, ecfg.max_context, jnp.float32)
    ctx, logits = llama.prefill(
        cfg, params, ctx, jnp.asarray(padded), jnp.int32(0),
        jnp.int32(0), jnp.int32(len(toks)),
        jnp.asarray(ov), jnp.asarray(msk),
    )
    ref = [int(np.argmax(np.asarray(logits)))]
    seq_len = len(toks)
    ring = llama.init_ring(cfg, 1, 1, dtype=jnp.float32)
    for _ in range(5):
        seq_len += 1
        rb = jnp.asarray([seq_len - 1], jnp.int32)
        ring, lg = llama.decode_step(
            cfg, params, ctx, ring, jnp.asarray([ref[-1]], jnp.int32),
            jnp.asarray([seq_len], jnp.int32), rb, jnp.int32(0),
        )
        ctx = llama.flush_ctx(ctx, ring, jnp.asarray([0], jnp.int32), rb,
                              jnp.asarray([1], jnp.int32))
        ref.append(int(np.argmax(np.asarray(lg)[0])))
    assert out == ref, "engine must match the explicit-embeds reference"

    # different image -> different prefill logits (embeddings really
    # reach the model; tiny random models may still argmax identically,
    # so compare the distribution, not sampled tokens)
    emb_b = np.asarray(encode_image(vcfg, vparams, jnp.asarray(image(1))),
                       np.float32)
    ov_b = ov.copy()
    ov_b[pos: pos + len(emb_b)] = emb_b
    ctx2 = llama.init_ctx(cfg, 1, ecfg.max_context, jnp.float32)
    _, logits_b = llama.prefill(
        cfg, params, ctx2, jnp.asarray(padded), jnp.int32(0),
        jnp.int32(0), jnp.int32(len(toks)),
        jnp.asarray(ov_b), jnp.asarray(msk),
    )
    assert not np.allclose(np.asarray(logits), np.asarray(logits_b))

    # same image again: prefix-cache may hit, output must stay identical
    out_c = await collect(eng, mm_request(vcfg, img))
    assert out_c == out
    await eng.stop()


async def test_multimodal_digest_prevents_cross_image_cache_hits(setup):
    """Two requests with IDENTICAL placeholder tokens but different images
    must not share prefix-cache blocks (the digest salt)."""
    cfg, vcfg, params, vparams, ecfg = setup
    inner = TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))
    enc = EncodeWorker(None, vcfg, vparams)
    eng = MultimodalEngine(inner, local_encoder=enc)

    out_a = await collect(eng, mm_request(vcfg, image(0)))
    hits_before = inner.allocator.hit_blocks
    out_b = await collect(eng, mm_request(vcfg, image(1)))
    assert inner.allocator.hit_blocks == hits_before, \
        "different image must MISS the prefix cache"
    assert len(out_b) == len(out_a) == 6
    await eng.stop()


async def test_multimodal_over_distributed_runtime(setup):
    """Full graph: encode worker registered on the runtime; the decode
    side resolves embeddings over the encode ENDPOINT (the reference's
    worker-to-worker embedding handoff)."""
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store import serve_store

    cfg, vcfg, params, vparams, ecfg = setup
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt_enc = await DistributedRuntime.connect(port=port)
    rt_dec = await DistributedRuntime.connect(port=port)
    enc = await EncodeWorker(rt_enc, vcfg, vparams, namespace="mm").start()
    inner = TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))
    eng = MultimodalEngine(inner, rt=rt_dec, namespace="mm")
    try:
        out = await collect(eng, mm_request(vcfg, image(0)))
        assert len(out) == 6
        assert enc.images_encoded == 1
    finally:
        await eng.stop()
        await enc.stop()
        await rt_dec.close()
        await rt_enc.close()
        server.close()


async def test_multimodal_http_image_lowering(setup):
    """HTTP surface: a chat message with an image content part is lowered
    to placeholder tokens + encode-worker payload by the preprocessor,
    resolved by the MultimodalEngine, and served end to end."""
    from aiohttp.test_utils import TestClient, TestServer

    from dynamo_tpu.backend import Backend
    from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
    from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
    from dynamo_tpu.tokenizer import make_test_tokenizer

    cfg, vcfg, params, vparams, ecfg = setup
    tok = make_test_tokenizer([f"w{i}" for i in range(60)])
    fmt = PromptFormatter(
        template="{% for m in messages %}{{ m.content }}{% endfor %}"
    )
    inner = TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))
    eng = MultimodalEngine(inner, local_encoder=EncodeWorker(None, vcfg, vparams))
    chain = ModelChain(
        name="mm",
        preprocessor=OpenAIPreprocessor(
            tokenizer=tok, formatter=fmt, model_name="mm",
            image_token_id=IMG_TOK, image_token_count=vcfg.num_patches,
        ),
        engine=eng,
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    try:
        img = image(3)
        payload = encode_image_payload(img)
        r = await client.post("/v1/chat/completions", json={
            "model": "mm",
            "messages": [{"role": "user", "content": [
                {"type": "text", "text": "w1 w2 "},
                {"type": "image_data", "data": payload["data"],
                 "shape": payload["shape"]},
                {"type": "text", "text": " w3"},
            ]}],
            "max_tokens": 5,
        })
        assert r.status == 200
        body = await r.json()
        assert body["usage"]["completion_tokens"] == 5
        # prompt tokens include the placeholder run
        assert body["usage"]["prompt_tokens"] >= vcfg.num_patches + 3
        assert eng.images_resolved == 1
        # non-data image URLs are rejected (zero-egress host)
        r2 = await client.post("/v1/chat/completions", json={
            "model": "mm",
            "messages": [{"role": "user", "content": [
                {"type": "image_url",
                 "image_url": {"url": "https://example.com/x.png"}},
            ]}],
            "max_tokens": 2,
        })
        assert r2.status == 400
    finally:
        await client.close()
        await eng.stop()


def test_vision_clip_checkpoint_roundtrip(tmp_path):
    """A CLIP-shape vision tower + LLaVA projector written as safetensors
    loads into the param tree with the right transposes (conv->patch
    matmul, torch [out,in] -> [in,out]) and runs a forward pass
    (reference: the encode worker serves a real LLaVA/Qwen-VL tower)."""
    import numpy as np
    from safetensors.numpy import save_file

    from dynamo_tpu.models.vision import (
        VisionConfig,
        encode_image,
        load_vision_params,
    )

    cfg = VisionConfig.tiny(use_class_token=True)
    rng = np.random.RandomState(0)
    H, I, P = cfg.hidden_size, cfg.intermediate_size, cfg.patch_size
    OUT = cfg.out_hidden_size
    sd = {
        "vision_tower.vision_model.embeddings.patch_embedding.weight":
            rng.randn(H, 3, P, P).astype(np.float32) * 0.05,
        "vision_tower.vision_model.embeddings.class_embedding":
            rng.randn(H).astype(np.float32) * 0.02,
        "vision_tower.vision_model.embeddings.position_embedding.weight":
            rng.randn(cfg.num_positions, H).astype(np.float32) * 0.02,
        "vision_tower.vision_model.pre_layrnorm.weight":
            np.ones(H, np.float32),
        "vision_tower.vision_model.pre_layrnorm.bias":
            np.zeros(H, np.float32),
        "vision_tower.vision_model.post_layernorm.weight":
            np.ones(H, np.float32),
        "vision_tower.vision_model.post_layernorm.bias":
            np.zeros(H, np.float32),
        "multi_modal_projector.linear_1.weight":
            rng.randn(OUT, H).astype(np.float32) * 0.05,
        "multi_modal_projector.linear_1.bias":
            np.zeros(OUT, np.float32),
        "multi_modal_projector.linear_2.weight":
            rng.randn(OUT, OUT).astype(np.float32) * 0.05,
        "multi_modal_projector.linear_2.bias":
            np.zeros(OUT, np.float32),
    }
    for l in range(cfg.num_layers):
        p = f"vision_tower.vision_model.encoder.layers.{l}."
        for nm, shp in (("layer_norm1", H), ("layer_norm2", H)):
            sd[p + nm + ".weight"] = np.ones(shp, np.float32)
            sd[p + nm + ".bias"] = np.zeros(shp, np.float32)
        for nm in ("q_proj", "k_proj", "v_proj", "out_proj"):
            sd[p + f"self_attn.{nm}.weight"] = (
                rng.randn(H, H).astype(np.float32) * 0.05)
            sd[p + f"self_attn.{nm}.bias"] = np.zeros(H, np.float32)
        sd[p + "mlp.fc1.weight"] = rng.randn(I, H).astype(np.float32) * 0.05
        sd[p + "mlp.fc1.bias"] = np.zeros(I, np.float32)
        sd[p + "mlp.fc2.weight"] = rng.randn(H, I).astype(np.float32) * 0.05
        sd[p + "mlp.fc2.bias"] = np.zeros(H, np.float32)
    save_file(sd, str(tmp_path / "model.safetensors"))

    params = load_vision_params(cfg, str(tmp_path))
    # transposes verified leaf-wise
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"][0]),
        sd["vision_tower.vision_model.encoder.layers.0.self_attn.q_proj.weight"].T,
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(params["proj"]),
        sd["multi_modal_projector.linear_1.weight"].T, rtol=1e-6,
    )
    conv = sd["vision_tower.vision_model.embeddings.patch_embedding.weight"]
    np.testing.assert_allclose(
        np.asarray(params["patch_embed"]),
        conv.transpose(2, 3, 1, 0).reshape(cfg.patch_dim, H), rtol=1e-6,
    )
    img = np.random.RandomState(1).rand(
        cfg.image_size, cfg.image_size, 3).astype(np.float32)
    out = np.asarray(encode_image(cfg, params, img))
    assert out.shape == (cfg.num_patches, OUT)
    assert np.isfinite(out).all()


async def test_rpc_embeddings_travel_as_array_frames(setup):
    """Over the distributed runtime, embeddings must ride the frame2
    array channel (tickets), not JSON float lists."""
    import numpy as np

    from dynamo_tpu.kv_transfer import take_remote_array
    from dynamo_tpu.multimodal import EncodeWorker, encode_image_payload
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store import serve_store

    cfg, vcfg, _params, vparams, _ecfg = setup
    server, _ = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    enc = await EncodeWorker(rt, vcfg, vparams).start()
    try:
        img = encode_image_payload(
            np.random.RandomState(0).rand(16, 16, 3).astype(np.float32))
        client = await rt.namespace("dynamo").component(
            "encoder").endpoint("encode").client()
        resp = None
        async for item in client.generate({"images": [img, img]}):
            resp = item
        ents = resp["embeddings"]
        assert all("ticket" in e and "data" not in e for e in ents)
        arr = await take_remote_array(
            ents[0]["host"], ents[0]["port"], ents[0]["ticket"])
        assert arr.shape == tuple(ents[0]["shape"])
        assert arr.dtype == np.float32
        # tickets are one-shot
        import pytest as _pytest

        from dynamo_tpu.kv_transfer import BlockTransferError
        with _pytest.raises(BlockTransferError):
            await take_remote_array(
                ents[0]["host"], ents[0]["port"], ents[0]["ticket"])
    finally:
        await enc.stop()
        await rt.close()
        server.close()
