"""Supervised critical tasks (reference utils/task.rs:42): restart with
backoff, budget exhaustion, clean stop."""
import asyncio

from dynamo_tpu.runtime.tasks import CriticalTask


async def test_restarts_with_backoff_then_recovers():
    runs = []

    async def flaky():
        runs.append(1)
        if len(runs) < 3:
            raise RuntimeError("boom")
        await asyncio.sleep(30)  # healthy long-runner

    t = CriticalTask(flaky, "t", backoff_base_s=0.01).start()
    for _ in range(200):
        if len(runs) >= 3:
            break
        await asyncio.sleep(0.01)
    assert len(runs) == 3 and t.running
    assert t.restarts == 2
    await t.stop()
    assert not t.running


async def test_gives_up_after_budget():
    gave_up = []

    async def always_fails():
        raise RuntimeError("nope")

    t = CriticalTask(
        always_fails, "t", max_restarts=2, backoff_base_s=0.01,
        on_give_up=gave_up.append,
    ).start()
    for _ in range(200):
        if gave_up:
            break
        await asyncio.sleep(0.01)
    assert len(gave_up) == 1
    assert t.failures == 3  # initial + 2 restarts


async def test_clean_completion_not_restarted():
    runs = []

    async def once():
        runs.append(1)

    t = CriticalTask(once, "t").start()
    await asyncio.sleep(0.05)
    assert runs == [1] and not t.running


async def test_planner_and_router_loops_supervised():
    """The adopting components expose supervised handles."""
    from dynamo_tpu.runtime.store import serve_store
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.router_service import RouterService
    from dynamo_tpu.planner import Planner, PlannerConfig
    from dynamo_tpu.runtime.client import KvClient

    server, _ = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    svc = await RouterService(rt, namespace="sv").start()
    assert svc._sub_task.running and svc._sweep_task.running
    await svc.stop()

    kv = await KvClient(port=port).connect()

    class _Conn:
        def current_replicas(self):
            return 1

        async def set_replicas(self, n):
            pass

    planner = await Planner(kv, _Conn(),
                            PlannerConfig(adjustment_interval_s=0.05)).start()
    assert planner._task.running and planner._sub_task.running
    await planner.stop()
    await kv.close()
    await rt.close()
    server.close()
