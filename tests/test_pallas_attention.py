"""Pallas paged decode attention vs the jnp reference (interpret mode, CPU).

The same kernel binary runs on real TPU; interpret mode validates the
kernel's math — online softmax accumulation, page-table indirection, layer
indexing, GQA head grouping, two-tier pool+ring masking — against
paged_decode_attention_reference.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.ops.attention import paged_decode_attention_reference
from dynamo_tpu.ops.pallas_attention import paged_decode_attention_pallas


@pytest.mark.parametrize(
    "B,nh,nkv,hd,ps,max_pages,R",
    [
        (4, 8, 2, 64, 16, 4, 4),    # GQA g=4
        (2, 4, 4, 32, 8, 3, 2),     # MHA g=1
        (3, 16, 8, 128, 8, 2, 8),   # llama-8B-like head geometry
    ],
)
def test_kernel_matches_reference(B, nh, nkv, hd, ps, max_pages, R):
    rng = np.random.RandomState(0)
    L = 3
    P = max_pages * B + 1
    q = jnp.asarray(rng.randn(B, nh, hd), jnp.float32)
    k_cache = jnp.asarray(rng.randn(L, nkv, P, ps, hd), jnp.float32)
    v_cache = jnp.asarray(rng.randn(L, nkv, P, ps, hd), jnp.float32)
    ring_k = jnp.asarray(rng.randn(L, nkv, B, R, hd), jnp.float32)
    ring_v = jnp.asarray(rng.randn(L, nkv, B, R, hd), jnp.float32)
    # each slot gets its own pages; ragged context lengths incl. unaligned.
    # The last 1..R positions live in the ring (ring_base = ctx - r_live).
    page_tables = np.zeros((B, max_pages), np.int32)
    ctx = np.zeros(B, np.int32)
    base = np.zeros(B, np.int32)
    for b in range(B):
        n = rng.randint(1, max_pages + 1)
        page_tables[b, :n] = rng.choice(np.arange(1, P), size=n, replace=False)
        ctx[b] = rng.randint(1, n * ps + 1)
        base[b] = ctx[b] - rng.randint(1, min(R, ctx[b]) + 1)
    pt = jnp.asarray(page_tables)
    cl = jnp.asarray(ctx)
    rb = jnp.asarray(base)

    for layer in (0, L - 1):
        li = jnp.int32(layer)
        ref = paged_decode_attention_reference(
            q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb
        )
        got = paged_decode_attention_pallas(
            q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_kernel_ring_only_context():
    """ctx entirely inside the ring (ring_base=0): pool pages contribute
    nothing; first decode steps after an empty-prefix admission hit this."""
    rng = np.random.RandomState(2)
    B, nh, nkv, hd, ps, R = 2, 4, 2, 32, 8, 4
    q = jnp.asarray(rng.randn(B, nh, hd), jnp.float32)
    k_cache = jnp.asarray(rng.randn(2, nkv, 5, ps, hd), jnp.float32)
    v_cache = jnp.asarray(rng.randn(2, nkv, 5, ps, hd), jnp.float32)
    ring_k = jnp.asarray(rng.randn(2, nkv, B, R, hd), jnp.float32)
    ring_v = jnp.asarray(rng.randn(2, nkv, B, R, hd), jnp.float32)
    pt = jnp.asarray(np.zeros((B, 3), np.int32))
    cl = jnp.asarray(np.array([2, R], np.int32))
    rb = jnp.asarray(np.zeros(B, np.int32))
    li = jnp.int32(0)
    ref = paged_decode_attention_reference(
        q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb
    )
    got = paged_decode_attention_pallas(
        q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_kernel_inactive_slot_all_zero_table():
    """Inactive decode slots: table all page-0, ctx=1, ring_base=0 — must
    not NaN (exactly one valid ring entry)."""
    rng = np.random.RandomState(1)
    B, R = 2, 4
    q = jnp.asarray(rng.randn(B, 4, 32), jnp.float32)
    k_cache = jnp.asarray(rng.randn(2, 2, 5, 8, 32), jnp.float32)
    v_cache = jnp.asarray(rng.randn(2, 2, 5, 8, 32), jnp.float32)
    ring_k = jnp.asarray(rng.randn(2, 2, B, R, 32), jnp.float32)
    ring_v = jnp.asarray(rng.randn(2, 2, B, R, 32), jnp.float32)
    pt = jnp.asarray(np.zeros((B, 3), np.int32))
    cl = jnp.asarray(np.array([1, 1], np.int32))
    rb = jnp.asarray(np.zeros(B, np.int32))
    li = jnp.int32(1)
    got = paged_decode_attention_pallas(
        q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb, interpret=True
    )
    ref = paged_decode_attention_reference(
        q, k_cache, v_cache, ring_k, ring_v, li, pt, cl, rb
    )
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
