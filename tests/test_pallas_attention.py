"""Pallas paged decode attention vs the jnp reference (interpret mode, CPU).

The same kernel binary runs on real TPU; interpret mode validates the
kernel's math — online softmax accumulation, page-table indirection, layer
indexing, GQA head grouping, context masking — against
paged_decode_attention_reference.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.ops.attention import paged_decode_attention_reference
from dynamo_tpu.ops.pallas_attention import paged_decode_attention_pallas


@pytest.mark.parametrize(
    "B,nh,nkv,hd,ps,max_pages",
    [
        (4, 8, 2, 64, 16, 4),    # GQA g=4
        (2, 4, 4, 32, 8, 3),     # MHA g=1
        (3, 16, 8, 128, 8, 2),   # llama-8B-like head geometry
    ],
)
def test_kernel_matches_reference(B, nh, nkv, hd, ps, max_pages):
    rng = np.random.RandomState(0)
    L = 3
    P = max_pages * B + 1
    q = jnp.asarray(rng.randn(B, nh, hd), jnp.float32)
    k_cache = jnp.asarray(rng.randn(L, nkv, P, ps, hd), jnp.float32)
    v_cache = jnp.asarray(rng.randn(L, nkv, P, ps, hd), jnp.float32)
    # each slot gets its own pages; ragged context lengths incl. unaligned
    page_tables = np.zeros((B, max_pages), np.int32)
    ctx = np.zeros(B, np.int32)
    for b in range(B):
        n = rng.randint(1, max_pages + 1)
        page_tables[b, :n] = rng.choice(np.arange(1, P), size=n, replace=False)
        ctx[b] = rng.randint(1, n * ps + 1)
    pt = jnp.asarray(page_tables)
    cl = jnp.asarray(ctx)

    for layer in (0, L - 1):
        li = jnp.int32(layer)
        ref = paged_decode_attention_reference(q, k_cache, v_cache, li, pt, cl)
        got = paged_decode_attention_pallas(
            q, k_cache, v_cache, li, pt, cl, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_kernel_inactive_slot_all_zero_table():
    """Inactive decode slots: table all page-0, ctx=1 — must not NaN."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 4, 32), jnp.float32)
    k_cache = jnp.asarray(rng.randn(2, 2, 5, 8, 32), jnp.float32)
    v_cache = jnp.asarray(rng.randn(2, 2, 5, 8, 32), jnp.float32)
    pt = jnp.asarray(np.zeros((2, 3), np.int32))
    cl = jnp.asarray(np.array([1, 1], np.int32))
    li = jnp.int32(1)
    got = paged_decode_attention_pallas(q, k_cache, v_cache, li, pt, cl, interpret=True)
    ref = paged_decode_attention_reference(q, k_cache, v_cache, li, pt, cl)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
