"""SLA profiler tests (reference benchmarks/profiler/profile_sla.py +
utils/perf_interpolation.py consumer)."""
import asyncio

from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.profiler import SlaCapacity, measure_point, profile_engine


def make_mocker(cfg: dict):
    return MockerEngine(MockerArgs(
        speedup_ratio=cfg.get("speedup_ratio", 50.0),
        max_decode_slots=cfg.get("max_decode_slots", 4),
        page_size=8, num_pages=256,
    ))


async def test_measure_point_shapes():
    eng = make_mocker({})
    pt = await measure_point(eng, concurrency=2, isl=16, osl=8, rounds=1)
    await eng.stop()
    assert pt.concurrency == 2
    assert pt.tok_s > 0
    assert pt.ttft_p50_s >= 0 and pt.ttft_p99_s >= pt.ttft_p50_s
    assert pt.itl_p50_s >= 0


async def test_profile_engine_sweeps_and_degrades():
    """More concurrency than slots must show worse (or equal) latency —
    the monotonicity the SLA capacity lookup depends on."""
    table = await profile_engine(
        make_mocker,
        [{"name": "slots2", "max_decode_slots": 2, "speedup_ratio": 5.0}],
        concurrencies=(1, 8),
        isl=16, osl=16, rounds=1,
    )
    pts = table["configs"][0]["points"]
    assert [p["concurrency"] for p in pts] == [1, 8]
    # 8 concurrent streams on 2 slots queue: TTFT must grow
    assert pts[1]["ttft_p50_s"] > pts[0]["ttft_p50_s"]


def test_sla_capacity_lookup():
    profile = {"configs": [{
        "name": "slots8",
        "points": [
            {"concurrency": 1, "ttft_p50_s": 0.01, "itl_p50_s": 0.005},
            {"concurrency": 4, "ttft_p50_s": 0.05, "itl_p50_s": 0.01},
            {"concurrency": 8, "ttft_p50_s": 0.50, "itl_p50_s": 0.05},
        ],
    }]}
    cap = SlaCapacity(profile, ttft_sla_s=0.1, itl_sla_s=0.02)
    assert cap.max_concurrency() == 4
    assert cap.replicas_for(0) == 1
    assert cap.replicas_for(4) == 1
    assert cap.replicas_for(5) == 2
    assert cap.replicas_for(12) == 3
    # unmeetable SLA: min_replicas, not a crash
    tight = SlaCapacity(profile, ttft_sla_s=0.001)
    assert tight.max_concurrency() == 0
    assert tight.replicas_for(100, min_replicas=2) == 2


async def test_planner_sla_mode():
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats,
    )
    from dynamo_tpu.planner import Planner, PlannerConfig
    from dynamo_tpu.runtime.client import KvClient
    from dynamo_tpu.runtime.store import serve_store

    server, store = await serve_store(port=0)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()

    class Conn:
        n = 1

        def current_replicas(self):
            return self.n

        async def set_replicas(self, n):
            self.n = n

    profile = {"configs": [{"name": "c", "points": [
        {"concurrency": 4, "ttft_p50_s": 0.01, "itl_p50_s": 0.005},
    ]}]}
    planner = Planner(
        kv, Conn(), PlannerConfig(min_replicas=1, max_replicas=5),
        sla=SlaCapacity(profile, ttft_sla_s=0.1),
    )
    # 10 observed streams at capacity 4/replica -> 3 replicas
    planner.aggregator.update(ForwardPassMetrics(
        worker_id="w0",
        worker_stats=WorkerStats(request_active_slots=6,
                                 num_requests_waiting=4),
        kv_stats=KvStats(),
    ))
    assert planner.decide() == 3
    # clamped by max_replicas
    planner.aggregator.update(ForwardPassMetrics(
        worker_id="w0",
        worker_stats=WorkerStats(request_active_slots=40),
        kv_stats=KvStats(),
    ))
    assert planner.decide() == 5

    # downscale is damped: a transient empty snapshot must not collapse
    # the fleet — one step down only after stable_intervals lows
    planner.connector.n = 5
    planner.aggregator.update(ForwardPassMetrics(
        worker_id="w0", worker_stats=WorkerStats(), kv_stats=KvStats(),
    ))
    assert planner.decide() == 5   # streak 1: hold
    assert planner.decide() == 4   # streak 2: one step
    await kv.close()
    server.close()
