"""Overload-protection plane (dynamo_tpu/overload/): bounded admission,
deadline-aware shedding, end-to-end backpressure, priority preemption.

The keystones:
  - intake past the queue budget bounces RETRIABLY end-to-end (typed
    wire frames, router spill to warm peers, HTTP 429 + Retry-After at
    the frontend) and a retry after the hint succeeds with no duplicate
    tokens;
  - a still-waiting request whose deadline passed sheds with ZERO
    tokens and the DEADLINE finish reason — never a mid-stream one;
  - preempting a running low-priority stream IS a forced migration:
    the victim's merged client stream is greedy token-identical to an
    uninterrupted run.
"""
import asyncio
import time

import numpy as np
import pytest

from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.overload import (
    OVERLOAD,
    AdmissionController,
    EngineOverloadedError,
    WorkerLoadView,
    apply_request_hints,
    mint_deadline,
    parse_priority,
)
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)

BS = 16


@pytest.fixture(autouse=True)
def _reset_overload():
    OVERLOAD.reset()
    yield
    OVERLOAD.reset()


def _req(tokens, max_tokens=8, priority=0, deadline=None):
    r = PreprocessedRequest(
        token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
    )
    r.priority = priority
    r.deadline = deadline
    return r


# ---------------------------------------------------------------------------
# AdmissionController / deadline helpers (pure units)


def test_admission_budgets_and_retry_after():
    adm = AdmissionController(max_waiting_requests=2,
                              max_waiting_prefill_tokens=100,
                              queue_wait_s=lambda: 0.4)
    assert adm.bounded
    adm.check(1, 50)  # under both budgets: fine
    with pytest.raises(EngineOverloadedError) as ei:
        adm.check(2, 0)  # depth at budget
    assert ei.value.retry_after_s == pytest.approx(0.8)
    with pytest.raises(EngineOverloadedError):
        adm.check(0, 100)  # token budget at budget
    # clamp: deep backlog never asks for more than the max window
    assert AdmissionController(
        1, 0, queue_wait_s=lambda: 100.0
    ).retry_after_s(50) == 30.0
    # floor: a barely-full queue never asks for a sub-500ms hammer
    assert AdmissionController(
        1, 0, queue_wait_s=lambda: 0.001
    ).retry_after_s(1) == 0.5
    # unbounded controller never raises
    AdmissionController(0, 0).check(10_000, 10_000_000)


def test_priority_and_deadline_parsing():
    assert parse_priority("high") == 1
    assert parse_priority("HIGH") == 1
    assert parse_priority("normal") == 0
    assert parse_priority("low") == 0
    assert parse_priority(1) == 1
    assert parse_priority("garbage") == 0
    assert parse_priority(None) == 0
    d = mint_deadline(250.0, now=1000.0)
    assert d == pytest.approx(1000.25)
    assert mint_deadline("nope") is None
    assert mint_deadline(-5) is None

    pre = _req([1, 2, 3])
    apply_request_hints(pre, None, {"priority": "high",
                                    "timeout_ms": 1000})
    assert pre.priority == 1
    assert pre.deadline is not None

    # headers override nvext
    class H(dict):
        pass

    pre2 = _req([1])
    apply_request_hints(
        pre2, {"X-Request-Priority": "normal",
               "X-Request-Timeout-Ms": "50"},
        {"priority": "high"},
    )
    assert pre2.priority == 0
    assert pre2.deadline == pytest.approx(time.time() + 0.05, abs=0.5)


def test_worker_load_view_saturation_cooldown_and_deadline():
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics,
        WorkerStats,
    )
    from dynamo_tpu.telemetry import TelemetryRegistry
    from dynamo_tpu.telemetry import metrics as tmetrics

    class Clock:
        now = 100.0

        def __call__(self):
            return self.now

    clk = Clock()
    view = WorkerLoadView(stale_after_s=5.0, clock=clk)

    def publish(wid, waiting, max_waiting, queue_s=None):
        hists = {}
        if queue_s is not None:
            reg = TelemetryRegistry()
            h = reg.histogram(*tmetrics.QUEUE)
            for _ in range(10):
                h.observe(queue_s)
            hists = reg.snapshot()
        view.observe(ForwardPassMetrics(
            worker_id=wid,
            worker_stats=WorkerStats(
                num_requests_waiting=waiting,
                max_waiting_requests=max_waiting,
            ),
            histograms=hists,
        ))

    publish("w0", waiting=3, max_waiting=4)
    assert not view.saturated("w0")
    publish("w0", waiting=4, max_waiting=4)
    assert view.saturated("w0")
    assert view.blocked(["w0", "w1"]) == {"w0"}
    # stale data never blocks
    clk.now += 10.0
    assert not view.saturated("w0")
    # live bounce cooldown blocks for exactly the hint window
    view.note_overloaded("w1", retry_after_s=2.0)
    assert view.saturated("w1")
    clk.now += 2.1
    assert not view.saturated("w1")
    # deadline skip: 5 waiting x ~1s observed queue wait >> 1s budget
    publish("w2", waiting=5, max_waiting=0, queue_s=1.0)
    assert view.cant_meet("w2", time.time() + 1.0)
    assert not view.cant_meet("w2", time.time() + 60.0)
    assert view.blocked(["w2"], deadline=time.time() + 1.0) == {"w2"}
    assert view.blocked(["w2"]) == set()


# ---------------------------------------------------------------------------
# Mocker engine: bounded admission + deadline shed (deterministic CPU)


async def test_mocker_bounded_admission_bounces_retriably():
    eng = MockerEngine(MockerArgs(
        page_size=BS, max_decode_slots=1, max_waiting_requests=1,
        prefill_time_per_token_s=0.002, decode_time_per_step_s=0.01,
    ))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 5000, 32).tolist() for _ in range(3)]

    async def drive(req):
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        return toks

    t1 = asyncio.ensure_future(drive(_req(prompts[0], max_tokens=12)))
    for _ in range(200):   # t1 admitted: holds the only slot
        if eng._active:
            break
        await asyncio.sleep(0.005)
    t2 = asyncio.ensure_future(drive(_req(prompts[1], max_tokens=12)))
    for _ in range(200):   # t2 waiting: the budget is now full
        if len(eng._waiting) >= 1:
            break
        await asyncio.sleep(0.005)
    with pytest.raises(EngineOverloadedError) as ei:
        async for _ in eng.generate(_req(prompts[2], max_tokens=12)):
            pass
    assert ei.value.retry_after_s >= 0.5
    assert OVERLOAD.get("dynamo_overload_rejected_total") == 1
    out1, out2 = await asyncio.gather(t1, t2)
    # retriable end-to-end: the bounced request retried on the
    # recovered engine succeeds with tokens identical to an unloaded
    # run (it was never admitted, so nothing ran twice)
    retried = await drive(_req(prompts[2], max_tokens=12))
    ref = MockerEngine(MockerArgs(page_size=BS, max_decode_slots=1))
    expected = []
    async for out in ref.generate(_req(prompts[2], max_tokens=12)):
        expected.extend(out.token_ids)
    assert retried == expected
    await ref.stop()
    await eng.stop()
    assert len(out1) == 12 and len(out2) == 12


async def test_mocker_deadline_shed_while_waiting():
    eng = MockerEngine(MockerArgs(
        page_size=BS, max_decode_slots=1,
        prefill_time_per_token_s=0.002, decode_time_per_step_s=0.02,
    ))
    rng = np.random.RandomState(1)
    long_req = _req(rng.randint(1, 5000, 32).tolist(), max_tokens=20)
    hog = asyncio.ensure_future(_drain(eng.generate(long_req)))
    for _ in range(200):
        if eng._active:
            break
        await asyncio.sleep(0.005)
    # expires while WAITING behind the hog
    doomed = _req(rng.randint(1, 5000, 16).tolist(), max_tokens=4,
                  deadline=time.time() + 0.05)
    outs = []
    async for out in eng.generate(doomed):
        outs.append(out)
    assert len(outs) == 1
    assert outs[0].finish_reason is FinishReason.DEADLINE
    assert outs[0].token_ids == []
    assert outs[0].annotations["shed"]["reason"] == "deadline"
    assert eng.sheds == 1
    assert OVERLOAD.get("dynamo_overload_shed_total") == 1
    await hog
    await eng.stop()


async def _drain(stream):
    toks = []
    async for out in stream:
        toks.extend(out.token_ids)
    return toks


# ---------------------------------------------------------------------------
# The wire: typed overloaded error frames over the endpoint plane


async def test_overload_error_propagates_over_the_wire():
    from dynamo_tpu.runtime.endpoint import EndpointServer, call_endpoint

    async def handler(payload):
        raise EngineOverloadedError("queue full", retry_after_s=7.5)
        yield  # pragma: no cover — makes this an async generator

    srv = EndpointServer(handler)
    host, port = await srv.start()
    with pytest.raises(EngineOverloadedError) as ei:
        async for _ in call_endpoint(host, port, {"x": 1}):
            pass
    # the typed class survives the hop WITH its hint, and stays a
    # ConnectionError so every retriable-error path treats it as one
    assert ei.value.retry_after_s == pytest.approx(7.5)
    assert isinstance(ei.value, ConnectionError)
    await srv.stop()


async def test_worker_draining_still_distinct_from_overload():
    from dynamo_tpu.resilience.drain import WorkerDrainingError
    from dynamo_tpu.runtime.endpoint import (
        EndpointConnectionError,
        EndpointServer,
        call_endpoint,
    )

    async def handler(payload):
        raise WorkerDrainingError("draining")
        yield  # pragma: no cover

    srv = EndpointServer(handler)
    host, port = await srv.start()
    with pytest.raises(EndpointConnectionError):
        async for _ in call_endpoint(host, port, {}):
            pass
    await srv.stop()


# ---------------------------------------------------------------------------
# Router: spill-before-shed + typed fleet-wide overload


class _OverloadedWorker:
    def __init__(self, retry_after_s=3.0):
        self.retry_after_s = retry_after_s
        self.attempts = 0

    async def generate(self, req):
        self.attempts += 1
        raise EngineOverloadedError("full", retry_after_s=self.retry_after_s)
        yield  # pragma: no cover


class _ServingWorker:
    def __init__(self):
        self.served = 0

    async def generate(self, req):
        self.served += 1
        for t in (11, 12, 13):
            yield LLMEngineOutput(token_ids=[t])
        yield LLMEngineOutput(token_ids=[],
                              finish_reason=FinishReason.LENGTH)


def _warm_indexer(router, wid, tokens):
    """Make `wid` the KV-warm (and therefore chosen) worker."""
    from dynamo_tpu.kv_router.protocols import (
        KvCacheEvent,
        KvEventKind,
        StoredBlock,
    )
    from dynamo_tpu.tokens import compute_block_hashes

    hashes = compute_block_hashes(tokens, BS)
    router.indexer.apply_event(KvCacheEvent(
        kind=KvEventKind.STORED, worker_id=wid,
        blocks=[StoredBlock(block_hash=h) for h in hashes],
    ))


async def test_router_spills_overload_to_peer_without_eviction():
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    full = _OverloadedWorker(retry_after_s=2.5)
    ok = _ServingWorker()
    push.add_worker("w_full", full)
    push.add_worker("w_ok", ok)
    prompt = list(range(1, 4 * BS + 1))
    _warm_indexer(router, "w_full", prompt)  # KV-warm: chosen first

    toks = await _drain(push.generate(_req(prompt)))
    assert toks == [11, 12, 13]
    assert full.attempts == 1 and ok.served == 1
    # the overloaded worker is NOT evicted (overload is transient) but
    # IS cooled down for its Retry-After window; exactly ONE spill is
    # counted per bounce
    assert "w_full" in push.workers
    assert push.load.saturated("w_full")
    assert OVERLOAD.get("dynamo_overload_router_spills_total") == 1
    # the cooldown steers the NEXT request away proactively
    toks2 = await _drain(push.generate(_req(prompt)))
    assert toks2 == [11, 12, 13]
    assert full.attempts == 1  # never re-tried inside the window
    # proactive steering is NOT a spill: the counter reports bounces
    assert OVERLOAD.get("dynamo_overload_router_spills_total") == 1


async def test_router_all_overloaded_raises_typed_error():
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    push.add_worker("w0", _OverloadedWorker(retry_after_s=4.0))
    push.add_worker("w1", _OverloadedWorker(retry_after_s=4.0))
    with pytest.raises(EngineOverloadedError) as ei:
        await _drain(push.generate(_req(list(range(1, BS + 1)))))
    assert ei.value.retry_after_s == pytest.approx(4.0)


async def test_router_proactive_spill_from_published_budgets():
    """Backpressure half: published queue-budget saturation steers
    routing BEFORE any bounce happens."""
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics,
        WorkerStats,
    )
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    full = _OverloadedWorker()
    ok = _ServingWorker()
    push.add_worker("w_full", full)
    push.add_worker("w_ok", ok)
    prompt = list(range(1, 4 * BS + 1))
    _warm_indexer(router, "w_full", prompt)
    # the metrics plane says w_full's queue budget is saturated
    push.load.observe(ForwardPassMetrics(
        worker_id="w_full",
        worker_stats=WorkerStats(num_requests_waiting=4,
                                 max_waiting_requests=4),
    ))
    toks = await _drain(push.generate(_req(prompt)))
    assert toks == [11, 12, 13]
    assert full.attempts == 0  # never even dispatched to
    # no bounce happened, so no spill is counted (the counter reports
    # live bounces, not every steered decision)
    assert OVERLOAD.get("dynamo_overload_router_spills_total") == 0


# ---------------------------------------------------------------------------
# TpuEngine: bounded admission, deadline shed, priority preemption


def _tiny_engine(**kw):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig

    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=128, page_size=BS, max_pages_per_seq=16,
        max_decode_slots=kw.pop("max_decode_slots", 1),
        prefill_buckets=(64,), cache_dtype="float32", **kw,
    )
    return TpuEngine(cfg, ecfg, params=kw.get("params"),
                     mesh_config=MeshConfig(tp=1)), cfg


async def test_engine_bounded_admission_and_recovery():
    eng, cfg = _tiny_engine(max_waiting_requests=1)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, 40).tolist()
               for _ in range(3)]
    hog = asyncio.ensure_future(
        _drain(eng.generate(_req(prompts[0], max_tokens=120)))
    )
    for _ in range(600):   # hog holds the only lane
        if any(s is not None for s in eng._slots) or eng._prefilling:
            break
        await asyncio.sleep(0.005)
    waiter = asyncio.ensure_future(
        _drain(eng.generate(_req(prompts[1], max_tokens=4)))
    )
    # budget (1 waiting) fills once the waiter queues behind the hog
    for _ in range(600):
        if (sum(1 for r in eng._waiting if r.slot < 0)
                + eng._intake.qsize()) >= 1:
            break
        await asyncio.sleep(0.005)
    with pytest.raises(EngineOverloadedError) as ei:
        await _drain(eng.generate(_req(prompts[2], max_tokens=4)))
    assert ei.value.retry_after_s >= 0.5
    assert OVERLOAD.get("dynamo_overload_rejected_total") == 1
    out0 = await hog
    await waiter
    assert len(out0) == 120
    # recovered: the same request admits now
    out2 = await _drain(eng.generate(_req(prompts[2], max_tokens=4)))
    assert len(out2) == 4
    await eng.stop()


async def test_engine_deadline_shed_while_waiting_no_tokens():
    eng, cfg = _tiny_engine()
    rng = np.random.RandomState(3)
    hog = asyncio.ensure_future(_drain(eng.generate(
        _req(rng.randint(1, cfg.vocab_size, 40).tolist(),
             max_tokens=180)
    )))
    for _ in range(600):
        if any(s is not None for s in eng._slots) or eng._prefilling:
            break
        await asyncio.sleep(0.005)
    doomed = _req(rng.randint(1, cfg.vocab_size, 24).tolist(),
                  max_tokens=8, deadline=time.time() + 0.02)
    outs = []
    async for out in eng.generate(doomed):
        outs.append(out)
    assert [o.finish_reason for o in outs] == [FinishReason.DEADLINE]
    assert outs[0].token_ids == []
    assert eng.sheds == 1
    assert OVERLOAD.get("dynamo_overload_shed_total") == 1
    await hog
    await eng.stop()


async def test_engine_high_priority_preempts_waiting_entry():
    eng, cfg = _tiny_engine(max_waiting_requests=1)
    rng = np.random.RandomState(4)
    hog = asyncio.ensure_future(_drain(eng.generate(
        _req(rng.randint(1, cfg.vocab_size, 40).tolist(),
             max_tokens=100)
    )))
    for _ in range(600):   # hog holds the only lane
        if any(s is not None for s in eng._slots) or eng._prefilling:
            break
        await asyncio.sleep(0.005)
    lowq = rng.randint(1, cfg.vocab_size, 24).tolist()
    low = asyncio.ensure_future(_drain(eng.generate(
        _req(lowq, max_tokens=4)
    )))
    for _ in range(600):
        if (sum(1 for r in eng._waiting if r.slot < 0)
                + eng._intake.qsize()) >= 1:
            break
        await asyncio.sleep(0.005)
    # high-priority arrival on a full queue: admitted anyway — the
    # waiting low-priority entry is evicted retriably in its place
    high = asyncio.ensure_future(_drain(eng.generate(
        _req(rng.randint(1, cfg.vocab_size, 24).tolist(),
             max_tokens=4, priority=1)
    )))
    with pytest.raises(EngineOverloadedError):
        await low
    assert OVERLOAD.get("dynamo_overload_preempted_total") == 1
    assert eng.waiting_preemptions == 1
    await hog
    out_high = await high
    assert len(out_high) == 4
    await eng.stop()


async def test_preemption_as_migration_greedy_token_identical():
    """Running half: a high-priority arrival force-migrates the running
    low-priority stream through the router's migration plane — the
    victim's merged client stream is token-identical to an unloaded
    run, and the high-priority request serves on the freed lane."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.resilience.metrics import RESILIENCE

    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)

    def mk(wid, preempt=False):
        return TpuEngine(cfg, EngineConfig(
            num_pages=128, page_size=BS, max_pages_per_seq=16,
            max_decode_slots=1, prefill_buckets=(64,),
            cache_dtype="float32", worker_id=wid,
            preempt_running=preempt,
        ), params=params, mesh_config=MeshConfig(tp=1))

    rng = np.random.RandomState(5)
    victim_prompt = rng.randint(1, cfg.vocab_size, 40).tolist()
    victim_req = _req(victim_prompt, max_tokens=100)

    # unloaded greedy reference
    ref = mk("ref")
    expected = await _drain(ref.generate(_req(victim_prompt,
                                              max_tokens=100)))
    await ref.stop()

    eng_a = mk("A", preempt=True)
    eng_b = mk("B")
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router)
    push.add_worker("A", eng_a)  # the only worker: victim lands here

    migrations_before = RESILIENCE.get("dynamo_migration_total")
    got: list[int] = []

    async def run_victim():
        async for out in push.generate(victim_req):
            got.extend(out.token_ids)

    vt = asyncio.ensure_future(run_victim())
    for _ in range(2000):
        if len(got) >= 8:
            break
        await asyncio.sleep(0.005)
    assert len(got) >= 8, "victim never started streaming"
    push.add_worker("B", eng_b)  # migration target
    # high-priority request straight at the saturated worker A
    high = asyncio.ensure_future(_drain(eng_a.generate(
        _req(rng.randint(1, cfg.vocab_size, 24).tolist(),
             max_tokens=6, priority=1)
    )))
    await vt
    out_high = await high
    assert got == expected, "merged stream must be token-identical"
    assert len(out_high) == 6
    assert eng_a.preempt_migrations == 1
    assert OVERLOAD.get("dynamo_overload_preempt_migrations_total") == 1
    assert (RESILIENCE.get("dynamo_migration_total")
            == migrations_before + 1)
    await eng_a.stop()
    await eng_b.stop()


async def test_engine_publishes_queue_budgets_in_metrics():
    eng, _cfg = _tiny_engine(max_waiting_requests=7,
                             max_waiting_prefill_tokens=4096)
    m = eng.metrics()
    assert m.worker_stats.max_waiting_requests == 7
    assert m.worker_stats.max_waiting_prefill_tokens == 4096
    assert m.worker_stats.num_waiting_prefill_tokens == 0
    await eng.stop()


# ---------------------------------------------------------------------------
# Export-stream idle timeout (carried satellite): a stalled receiver's
# stream is reclaimed after the idle window, not the full xfer deadline


def test_export_stream_idle_timeout_reclaims_stalled_stream():
    eng, _cfg = _tiny_engine(kv_transfer_stream_idle_timeout_s=0.3)
    eng.start()
    stream = eng.export_pages_stream([1, 2, 3, 4], chunk_pages=1,
                                     inflight=1)
    # stall: consume nothing past the double-buffer for > idle window
    time.sleep(1.2)
    with pytest.raises(RuntimeError, match="abandoned"):
        for _ in stream:
            pass
    asyncio.run(eng.stop())


# ---------------------------------------------------------------------------
# Chaos: the storm injection mode


async def test_chaos_storm_bounces_with_retry_after():
    from dynamo_tpu.resilience.chaos import CHAOS

    CHAOS.reset()
    CHAOS.arm("storm", delay_s=2.5, once=True)

    async def src():
        yield {"t": 1}

    with pytest.raises(EngineOverloadedError) as ei:
        async for _ in CHAOS.wrap_stream(src()):
            pass
    assert ei.value.retry_after_s == pytest.approx(2.5)
    assert not CHAOS.points["storm"].armed  # once-fuse consumed
    # disarmed: the stream flows
    items = []
    async for item in CHAOS.wrap_stream(src()):
        items.append(item)
    assert items == [{"t": 1}]
    CHAOS.reset()


# ---------------------------------------------------------------------------
# Shared breaker state across frontends (carried satellite)


async def test_breaker_trips_share_across_frontends():
    from dynamo_tpu.resilience.health import WorkerHealthTracker
    from dynamo_tpu.resilience.shared import SharedBreakerBoard
    from dynamo_tpu.runtime.client import KvClient
    from dynamo_tpu.runtime.store import serve_store

    server, _store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    kv_a = await KvClient("127.0.0.1", port).connect()
    kv_b = await KvClient("127.0.0.1", port).connect()
    health_a = WorkerHealthTracker(failure_threshold=2,
                                   reset_timeout_s=30.0)
    health_b = WorkerHealthTracker(failure_threshold=2,
                                   reset_timeout_s=30.0)
    board_a = await SharedBreakerBoard(kv_a, health_a, "t").start()
    board_b = await SharedBreakerBoard(kv_b, health_b, "t").start()

    # frontend A pays the discovery cost; B learns without any failures
    health_a.record_failure("w0")
    health_a.record_failure("w0")
    for _ in range(100):
        if "w0" in health_b.blocked(["w0"]):
            break
        await asyncio.sleep(0.02)
    assert "w0" in health_b.blocked(["w0"])
    # B's own breaker saw no evidence — only the advisory remote block
    assert health_b.states().get("w0") is None
    # A's recovery probe succeeds: the close lifts B's block early
    # (without it, B stays blocked for the full 30s window)
    health_a.breaker("w0").begin_probe()
    health_a.record_success("w0")
    for _ in range(100):
        if "w0" not in health_b.blocked(["w0"]):
            break
        await asyncio.sleep(0.02)
    assert "w0" not in health_b.blocked(["w0"])
    await board_a.stop()
    await board_b.stop()
    await kv_a.close()
    await kv_b.close()
    server.close()


# ---------------------------------------------------------------------------
# Frontend: HTTP 429 + Retry-After, header minting


class _OverloadedEngine:
    async def generate(self, req):
        raise EngineOverloadedError("engine overloaded: queue at budget",
                                    retry_after_s=3.2)
        yield  # pragma: no cover


class _CaptureEngine:
    def __init__(self):
        self.last = None

    async def generate(self, req):
        self.last = req
        yield LLMEngineOutput(token_ids=[5],
                              finish_reason=FinishReason.LENGTH)


def _service(engine):
    from dynamo_tpu.backend import Backend
    from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
    from dynamo_tpu.preprocessor import (
        OpenAIPreprocessor,
        PromptFormatter,
    )
    from dynamo_tpu.tokenizer import make_test_tokenizer

    tok = make_test_tokenizer([f"w{i}" for i in range(30)])
    chain = ModelChain(
        name="m",
        preprocessor=OpenAIPreprocessor(tokenizer=tok,
                                        formatter=PromptFormatter(),
                                        model_name="m"),
        engine=engine,
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    return HttpService(manager)


async def _client(svc):
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(svc.app))
    await client.start_server()
    return client


async def test_frontend_unary_429_with_retry_after():
    svc = _service(_OverloadedEngine())
    client = await _client(svc)
    r = await client.post("/v1/chat/completions", json={
        "model": "m",
        "messages": [{"role": "user", "content": "w1 w2"}],
        "max_tokens": 4,
    })
    assert r.status == 429
    assert r.headers["Retry-After"] == "4"  # ceil(3.2)
    body = await r.json()
    assert body["error"]["type"] == "overloaded_error"
    assert OVERLOAD.get("dynamo_overload_http_429_total") == 1
    # 429s land in the request counter under their real status
    text = (await (await client.get("/metrics")).text())
    assert 'status="429"' in text
    assert "dynamo_overload_http_429_total 1" in text
    await client.close()


async def test_frontend_streaming_429_before_sse_prepare():
    svc = _service(_OverloadedEngine())
    client = await _client(svc)
    r = await client.post("/v1/chat/completions", json={
        "model": "m",
        "messages": [{"role": "user", "content": "w1"}],
        "max_tokens": 4,
        "stream": True,
    })
    # a clean retriable 429 — NOT a 200 SSE stream carrying an error
    assert r.status == 429
    assert "Retry-After" in r.headers
    body = await r.json()
    assert body["error"]["code"] == 429
    await client.close()


async def test_frontend_mints_priority_and_deadline_from_headers():
    cap = _CaptureEngine()
    svc = _service(cap)
    client = await _client(svc)
    t0 = time.time()
    r = await client.post(
        "/v1/chat/completions",
        json={"model": "m",
              "messages": [{"role": "user", "content": "w1"}],
              "max_tokens": 1},
        headers={"X-Request-Priority": "high",
                 "X-Request-Timeout-Ms": "30000"},
    )
    assert r.status == 200
    assert cap.last is not None
    assert cap.last.priority == 1
    assert cap.last.deadline == pytest.approx(t0 + 30.0, abs=2.0)
    # nvext path (no headers)
    await client.post(
        "/v1/chat/completions",
        json={"model": "m",
              "messages": [{"role": "user", "content": "w1"}],
              "max_tokens": 1,
              "nvext": {"priority": 1, "timeout_ms": 5000}},
    )
    assert cap.last.priority == 1
    assert cap.last.deadline == pytest.approx(time.time() + 5.0, abs=2.0)
    await client.close()


async def test_frontend_deadline_finish_reason_maps_to_stop():
    """A DEADLINE shed surfaces as a completed (empty) response, not an
    HTTP error — the request's budget ran out, nothing failed."""

    class _ShedEngine:
        async def generate(self, req):
            yield LLMEngineOutput(
                token_ids=[], finish_reason=FinishReason.DEADLINE,
                annotations={"shed": {"reason": "deadline"}},
            )

    svc = _service(_ShedEngine())
    client = await _client(svc)
    r = await client.post("/v1/chat/completions", json={
        "model": "m",
        "messages": [{"role": "user", "content": "w1"}],
        "max_tokens": 4,
    })
    assert r.status == 200
    body = await r.json()
    assert body["choices"][0]["finish_reason"] == "stop"
    assert body["usage"]["completion_tokens"] == 0
    await client.close()
