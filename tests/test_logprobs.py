"""Logprobs end-to-end (reference protocols/openai logprobs plumbing +
engines.rs logprobs): fused-step computation on the engine, token-string
entries in the backend, OpenAI shapes over HTTP (unary + SSE).
"""
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.backend import Backend
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.protocols.common import (
    OutputOptions,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.tokenizer import make_test_tokenizer

PS = 16
WORDS = [f"w{i}" for i in range(100)]


# ---------------------------------------------------------------------------
# engine level


async def test_engine_logprobs_greedy_consistency():
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=32, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", max_logprobs=5,
    )
    eng = TpuEngine(cfg, ecfg, params=llama.init_params(cfg, 0),
                    mesh_config=MeshConfig(tp=1))
    req = PreprocessedRequest(
        token_ids=list(range(1, 20)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        output_options=OutputOptions(logprobs=3),
    )
    # outputs may batch several tokens (round-granular emission); the
    # per-token logprob lists stay aligned with token_ids
    tokens, lps, tops_all = [], [], []
    async for out in eng.generate(req):
        if out.token_ids:
            assert out.log_probs is not None
            assert len(out.log_probs) == len(out.token_ids)
            assert len(out.top_logprobs) == len(out.token_ids)
            tokens.extend(out.token_ids)
            lps.extend(out.log_probs)
            tops_all.extend(out.top_logprobs)
    assert len(tokens) == 6 and len(lps) == 6
    for tok_id, lp, tops in zip(tokens, lps, tops_all):
        assert len(tops) == 3
        # greedy: the chosen token IS the top-1 alternative, same logprob
        assert tops[0][0] == tok_id
        assert abs(tops[0][1] - lp) < 1e-5
        assert lp <= 0.0
        # top list is sorted descending
        assert tops[0][1] >= tops[1][1] >= tops[2][1]

    # requests NOT asking for logprobs don't get them
    req2 = PreprocessedRequest(
        token_ids=list(range(1, 20)),
        stop_conditions=StopConditions(max_tokens=3, ignore_eos=True),
    )
    async for out in eng.generate(req2):
        assert out.log_probs is None
    await eng.stop()


# ---------------------------------------------------------------------------
# HTTP level (mocker synthesizes shaped logprobs)


def make_mock_service() -> HttpService:
    tok = make_test_tokenizer(WORDS)
    fmt = PromptFormatter(
        template="{% for m in messages %}{{ m.content }} {% endfor %}"
    )
    chain = ModelChain(
        name="mock",
        preprocessor=OpenAIPreprocessor(
            tokenizer=tok, formatter=fmt, model_name="mock"
        ),
        engine=MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=4)),
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    return HttpService(manager)


async def test_http_chat_logprobs_unary():
    svc = make_mock_service()
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/chat/completions", json={
        "model": "mock",
        "messages": [{"role": "user", "content": "w1 w2 w3"}],
        "max_tokens": 4,
        "logprobs": True,
        "top_logprobs": 2,
    })
    assert r.status == 200
    body = await r.json()
    lp = body["choices"][0]["logprobs"]
    assert lp is not None and "content" in lp
    assert len(lp["content"]) == 4
    for entry in lp["content"]:
        assert set(entry) >= {"token", "logprob", "bytes", "top_logprobs"}
        assert isinstance(entry["token"], str)
        assert entry["logprob"] <= 0.0
        assert len(entry["top_logprobs"]) == 2
        for t in entry["top_logprobs"]:
            assert set(t) >= {"token", "logprob"}
    # without the flag: null logprobs
    r2 = await client.post("/v1/chat/completions", json={
        "model": "mock",
        "messages": [{"role": "user", "content": "w1"}],
        "max_tokens": 2,
    })
    assert (await r2.json())["choices"][0]["logprobs"] is None
    await client.close()


async def test_http_completions_logprobs_unary():
    svc = make_mock_service()
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/completions", json={
        "model": "mock",
        "prompt": "w1 w2 w3",
        "max_tokens": 3,
        "logprobs": 2,
    })
    assert r.status == 200
    body = await r.json()
    lp = body["choices"][0]["logprobs"]
    assert lp is not None
    assert len(lp["tokens"]) == 3
    assert len(lp["token_logprobs"]) == 3
    assert all(v <= 0 for v in lp["token_logprobs"])
    assert len(lp["top_logprobs"]) == 3
    assert all(isinstance(d, dict) and len(d) == 2 for d in lp["top_logprobs"])
    await client.close()


async def test_http_chat_logprobs_streaming():
    svc = make_mock_service()
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/chat/completions", json={
        "model": "mock",
        "messages": [{"role": "user", "content": "w1 w2 w3"}],
        "max_tokens": 4,
        "logprobs": True,
        "top_logprobs": 1,
        "stream": True,
    })
    assert r.status == 200
    dec = SseDecoder()
    entries = []
    for ev in dec.feed(await r.read()):
        if ev.is_done:
            continue
        chunk = json.loads(ev.data)
        for choice in chunk.get("choices", []):
            if choice.get("logprobs"):
                entries.extend(choice["logprobs"]["content"])
    assert len(entries) == 4
    assert all(e["logprob"] <= 0 and len(e["top_logprobs"]) == 1
               for e in entries)
    await client.close()
