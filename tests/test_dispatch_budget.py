"""Dispatch-budget regression pins: the decode-round dispatch diet.

BENCH_r06 showed 6.53 ms wall/step vs 1.04 ms device/step — the gap is
host tax, and a big slice of it is per-round host→device dispatches.
After the diet (seals fused into the round program, packed patch
uploads, packed logprob fetches, metrics publish throttled), a steady
decode round costs exactly ONE program dispatch + ONE stacked-token
fetch. These tests pin that budget via the engine's own
``dispatch_counts`` accounting so future PRs can't silently regrow it
(the tool view of the same numbers: ``tools/profile_round.py
--dispatch-budget``).
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    OutputOptions,
    PreprocessedRequest,
    StopConditions,
)

PS = 16


def _engine(**kw) -> TpuEngine:
    base = dict(
        num_pages=128, page_size=PS, max_pages_per_seq=16,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32",
    )
    base.update(kw)
    return TpuEngine(ModelConfig.tiny(dtype="float32"),
                     EngineConfig(**base),
                     mesh_config=MeshConfig(tp=1))


async def _steady_window_budget(adapter_ids=None, setup=None, **kw):
    eng = _engine(**kw)
    if setup is not None:
        setup(eng)
    eng.start()
    rng = np.random.RandomState(0)
    n_req, osl = 4, 64
    prompts = [rng.randint(1, 256, 48).tolist() for _ in range(n_req)]
    progress = [0] * n_req

    async def one(i):
        async for out in eng.generate(PreprocessedRequest(
            token_ids=list(prompts[i]),
            stop_conditions=StopConditions(max_tokens=osl,
                                           ignore_eos=True),
            adapter_id=(adapter_ids[i % len(adapter_ids)]
                        if adapter_ids else 0),
            # variant requests carry their own model salt (the frontend
            # contract) so adapter streams never share cached prefixes
            model=(f"m:a{adapter_ids[i % len(adapter_ids)]}"
                   if adapter_ids else ""),
        )):
            progress[i] += len(out.token_ids)

    tasks = [asyncio.ensure_future(one(i)) for i in range(n_req)]
    # window opens once every request is admitted and decoding...
    while not all(p >= 4 for p in progress):
        await asyncio.sleep(0.005)
    d0 = dict(eng.dispatch_counts)
    # ...and closes well before any finishes (the dispatch front runs
    # ahead of emitted tokens by the pipeline lag — flush_every *
    # (max_inflight_rounds + 1) = 12 steps — so closing 20 tokens short
    # of osl keeps release patches out of the window)
    while not any(p >= osl - 20 for p in progress):
        await asyncio.sleep(0.005)
    d1 = dict(eng.dispatch_counts)
    await asyncio.gather(*tasks)
    await eng.stop()

    delta = {k: d1[k] - d0.get(k, 0) for k in d1}
    rounds = delta["round"] + delta["round_seal"]
    # the dispatch front leads emitted progress by the pipeline lag, so
    # the window captures a variable-but-positive round count
    assert rounds >= 5, delta
    # nothing but round programs + their fetches in the window
    assert delta["seal"] == 0, delta          # seals fused, not standalone
    assert delta["patch"] == 0, delta         # no admissions/releases
    assert delta["prefill"] == 0 and delta["prefill_batch"] == 0, delta
    assert delta["load_ctx"] == 0 and delta["sample_first"] == 0, delta
    total = sum(delta.values())
    # 1 program + 1 fetch per round; the snapshot can land between a
    # round's program and fetch increments, so allow one straggler
    # fetch per window edge
    assert total <= 2 * rounds + 2, (total, rounds, delta)
    # blocks complete every PS tokens: with 4 slots x 4 steps/round the
    # fused-seal variant must actually be exercised in the window
    assert delta["round_seal"] >= 1, delta


async def test_steady_decode_round_budget():
    """THE pin: in a steady decode window (every slot active, no
    admissions/releases/transfers), dispatches-per-round must stay at
    1 program + 1 fetch — and seals must ride the round program, never
    a standalone seal_blocks dispatch."""
    await _steady_window_budget()


async def test_steady_decode_round_budget_int8():
    """kv_quant=int8 keeps the identical budget: ring-flush
    requantization and the raw int8 fused seals all ride the round
    program — the in-kernel quant path costs ZERO extra dispatches."""
    await _steady_window_budget(kv_quant="int8")


async def test_steady_decode_round_budget_mixed_adapters():
    """Resident LoRA multiplexing keeps the identical budget: per-slot
    adapter rows are gathered INSIDE the fused round program, so a
    steady decode batch mixing the base model with two live fine-tune
    variants still costs 1 program + 1 fetch per round — adapter
    switching has no dispatch price."""
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.tenancy.adapters import random_adapter

    def setup(eng):
        mc = ModelConfig.tiny(dtype="float32")
        eng.install_adapter(1, random_adapter(mc, 4, seed=5))
        eng.install_adapter(2, random_adapter(mc, 4, seed=6))

    await _steady_window_budget(adapter_ids=(0, 1, 2, 1), setup=setup,
                                lora_adapters=4, lora_rank=4)


async def test_steady_decode_round_budget_tree_spec_configured():
    """Enabling tree speculation must not tax streams that never
    speculate: adapter-variant requests (speculation is confined to the
    base model) keep the exact 1-program + 1-fetch steady round with
    --spec-tree configured on the engine."""
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.tenancy.adapters import random_adapter

    def setup(eng):
        mc = ModelConfig.tiny(dtype="float32")
        eng.install_adapter(1, random_adapter(mc, 4, seed=5))

    await _steady_window_budget(
        adapter_ids=(1, 1, 1, 1), setup=setup,
        lora_adapters=4, lora_rank=4,
        speculative="ngram", num_speculative_tokens=4,
        spec_tree=True, spec_branches=2,
    )


async def test_spec_tree_steady_budget():
    """Tree-speculating slots hold the linear verify's fetch budget: one
    verify program + ONE packed fetch per tree round (tokens + accepted
    path + count + PRNG key in a single array), zero draft dispatches on
    the host-side n-gram proposer, and no stray patches/seals — with
    every slot speculating, no fused round programs run at all."""
    eng = _engine(speculative="ngram", num_speculative_tokens=4,
                  spec_tree=True, spec_branches=2, spec_adaptive=False)
    eng.start()
    rng = np.random.RandomState(0)
    pat = rng.randint(1, 256, 8).tolist()
    n_req, osl = 4, 64
    progress = [0] * n_req

    async def one(i):
        async for out in eng.generate(PreprocessedRequest(
            # repetitive prompts: the n-gram trie proposes real trees and
            # acceptance stays high, so slots never de-speculate
            token_ids=pat * 4,
            stop_conditions=StopConditions(max_tokens=osl,
                                           ignore_eos=True),
            model=f"m:{i}",  # distinct prefixes -> four live slots
        )):
            progress[i] += len(out.token_ids)

    tasks = [asyncio.ensure_future(one(i)) for i in range(n_req)]
    while not all(p >= 8 for p in progress):
        await asyncio.sleep(0.005)
    d0 = dict(eng.dispatch_counts)
    while not any(p >= osl - 24 for p in progress):
        await asyncio.sleep(0.005)
    d1 = dict(eng.dispatch_counts)
    await asyncio.gather(*tasks)
    await eng.stop()

    delta = {k: d1[k] - d0.get(k, 0) for k in d1}
    g = lambda k: delta.get(k, 0)
    assert g("spec_verify") >= 3, delta
    # the packed result array is the ONLY fetch a tree round makes
    # (snapshot can land between a verify's program and fetch
    # increments: allow one straggler per window edge)
    assert abs(g("fetch") - g("spec_verify")) <= 1, delta
    assert g("spec_draft") == 0, delta        # n-gram proposes on host
    assert g("round") == 0 and g("round_seal") == 0, delta
    assert g("patch") == 0, delta
    # speculating slots seal completed blocks via the standalone batched
    # copy (no fused round runs to carry them — the linear-chain path
    # pays the same); bound it by the blocks that can actually complete
    assert g("seal") <= (n_req * osl) // PS, delta
    assert g("prefill") == 0 and g("prefill_batch") == 0, delta


async def test_whole_run_dispatch_budget():
    """Coarse whole-workload pin (admission + prefill + decode + tail):
    the all-in dispatches-per-round number the profile tool reports.
    Pre-diet this sat around ~4.5 (one standalone seal nearly every
    round); pin at 4.0 with the measured value ~3.5."""
    eng = _engine()
    eng.start()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, 48).tolist() for _ in range(4)]

    async def one(p, mt):
        async for _ in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=mt,
                                           ignore_eos=True),
        )):
            pass

    # warmup compiles, then the measured window
    await asyncio.gather(*[one(p, 8) for p in prompts])
    d0 = dict(eng.dispatch_counts)
    await asyncio.gather(*[one(p, 40) for p in prompts])
    delta = {k: v - d0.get(k, 0) for k, v in eng.dispatch_counts.items()}
    await eng.stop()
    rounds = delta["round"] + delta["round_seal"]
    assert rounds >= 8, delta
    assert sum(delta.values()) / rounds <= 4.0, delta


async def test_logprob_fetch_is_packed():
    """Logprob rounds fetch ONE packed array (chosen + ids + lps), not
    three — and the unpacked values are self-consistent."""
    eng = _engine()
    eng.start()
    rng = np.random.RandomState(2)
    toks, lps, top = [], [], []
    async for out in eng.generate(PreprocessedRequest(
        token_ids=rng.randint(1, 256, 24).tolist(),
        stop_conditions=StopConditions(max_tokens=12, ignore_eos=True),
        output_options=OutputOptions(logprobs=2),
    )):
        toks.extend(out.token_ids)
        lps.extend(out.log_probs or [])
        top.extend(out.top_logprobs or [])
    await eng.stop()
    assert len(toks) == 12 and len(lps) == 12 and len(top) == 12
    for t, lp, pairs in zip(toks, lps, top):
        assert len(pairs) == 2
        # ids survived the f32 packing exactly; greedy chosen == top-1
        assert pairs[0][0] == t
        assert lp == pytest.approx(pairs[0][1], abs=1e-5)
        assert pairs[0][1] >= pairs[1][1]


async def test_fused_seal_round_matches_standalone_pin():
    """Correctness pin for the seal fusion: tokens + the prefix cache a
    fused-seal run produces are identical to what the engine produced
    before the fusion — verified by the warm wave hitting the sealed
    blocks (exact bf16 pool roundtrip) and by forcing a standalone
    flush path via an offload-tier engine (which flushes seals before
    its pool-reading gathers)."""
    outs = {}
    for mode, kw in (("fused", {}),
                     ("standalone", {"host_offload_pages": 16})):
        eng = _engine(**kw)
        eng.start()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 256, 3 * PS + 1).tolist()
                   for _ in range(2)]

        async def one(p):
            got = []
            async for out in eng.generate(PreprocessedRequest(
                token_ids=list(p),
                stop_conditions=StopConditions(max_tokens=8,
                                               ignore_eos=True),
            )):
                got.extend(out.token_ids)
            return got

        w1 = [await one(p) for p in prompts]
        w2 = [await one(p) for p in prompts]  # prefix-hit via the pool
        assert w1 == w2  # bf16 pool: byte-exact roundtrip either path
        outs[mode] = (w1, dict(eng.dispatch_counts))
        await eng.stop()
    assert outs["fused"][0] == outs["standalone"][0]
    # the fused variant was actually exercised (whether the offload
    # engine's pool-reading gathers forced standalone flushes is
    # timing-dependent; token identity above is the invariant)
    assert outs["fused"][1]["round_seal"] >= 1
