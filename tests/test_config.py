"""Config layering + logging init tests (reference config.rs figment
layering and logging.rs DYN_LOG filters)."""
import json
import logging

from dynamo_tpu.config import (
    JsonlFormatter,
    RuntimeConfig,
    _apply_filters,
    load_config,
)


def test_defaults():
    cfg = load_config(env={})
    assert cfg == RuntimeConfig()
    assert cfg.store_host_port == ("127.0.0.1", 7111)


def test_toml_layer(tmp_path):
    p = tmp_path / "conf.toml"
    p.write_text("""
[runtime]
control_plane = "10.0.0.9:7222"
page_size = 32
""")
    cfg = load_config(path=str(p), env={})
    assert cfg.control_plane == "10.0.0.9:7222"
    assert cfg.page_size == 32
    assert cfg.num_pages == 512  # untouched default


def test_env_overrides_toml(tmp_path):
    p = tmp_path / "conf.toml"
    p.write_text('[runtime]\npage_size = 32\nnamespace = "from-toml"\n')
    cfg = load_config(env={
        "DYNTPU_CONFIG": str(p),
        "DYNTPU_PAGE_SIZE": "128",
        "DYNTPU_HOST_OFFLOAD_PAGES": "64",
    })
    assert cfg.page_size == 128          # env wins over toml
    assert cfg.namespace == "from-toml"  # toml wins over default
    assert cfg.host_offload_pages == 64


def test_log_filter_spec():
    root = logging.getLogger("test-root-sentinel")
    _apply_filters("debug", root)
    assert root.level == logging.DEBUG
    _apply_filters("dynamo_tpu.x=warning, other.y=error", root)
    assert logging.getLogger("dynamo_tpu.x").level == logging.WARNING
    assert logging.getLogger("other.y").level == logging.ERROR


def test_jsonl_formatter():
    rec = logging.LogRecord(
        "pkg.mod", logging.WARNING, "f.py", 1, "something %s", ("bad",), None
    )
    out = json.loads(JsonlFormatter().format(rec))
    assert out["level"] == "WARNING"
    assert out["logger"] == "pkg.mod"
    assert out["msg"] == "something bad"
