"""KV block transfer plane (NIXL equivalent) tests.

The keystone test moves REAL prefilled KV pages between two engines' pools
over the TCP data plane and proves the receiving engine decodes from the
transferred prefix bit-exactly — the correctness core of disaggregated
prefill/decode (reference block_manager.rs:54,120-130, utils/nixl.py:116).
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_transfer import (
    BlocksetDescriptor,
    BlockTransferServer,
    KvCacheLayout,
    get_descriptor,
    publish_descriptor,
    read_remote_pages,
    write_remote_pages,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store
from dynamo_tpu.tokens import TokenBlockSequence

PS = 16


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64),
        cache_dtype="float32", worker_id="w",
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def mk_engine(setup, wid):
    cfg, ecfg, params = setup
    from dataclasses import replace

    return TpuEngine(
        cfg, replace(ecfg, worker_id=wid), params=params,
        mesh_config=MeshConfig(tp=1),
    )


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


# ---------------------------------------------------------------------------
# raw server/client roundtrip


async def test_transfer_server_roundtrip():
    pool = {"data": np.zeros((2, 2, 1, 8, PS, 4), np.float32)}

    def read_fn(pages):
        return pool["data"][:, :, :, pages]

    def write_fn(pages, data):
        pool["data"][:, :, :, pages] = data

    srv = BlockTransferServer(read_fn=read_fn, write_fn=write_fn)
    host, port = await srv.start()

    payload = np.random.default_rng(0).standard_normal(
        (2, 2, 1, 3, PS, 4)
    ).astype(np.float32)
    await write_remote_pages(host, port, [1, 4, 6], payload)
    got = await read_remote_pages(host, port, [1, 4, 6])
    np.testing.assert_array_equal(got, payload)
    # untouched pages stay zero
    assert np.all(pool["data"][:, :, :, 2] == 0)
    await srv.stop()


async def test_transfer_server_error_in_band():
    srv = BlockTransferServer(read_fn=None, write_fn=None)
    host, port = await srv.start()
    from dynamo_tpu.kv_transfer import BlockTransferError

    with pytest.raises(BlockTransferError):
        await read_remote_pages(host, port, [0])
    await srv.stop()


# ---------------------------------------------------------------------------
# descriptor metadata via the store


async def test_descriptor_publish_and_fetch():
    server, store = await serve_store(port=0)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    desc = BlocksetDescriptor(
        worker_id="w7", host="10.0.0.3", port=4242,
        layout=KvCacheLayout(num_layers=2, num_kv_heads=1, page_size=16,
                             head_dim=4, dtype="float32"),
    )
    await publish_descriptor(kv, "dynamo", desc)
    got = await get_descriptor(kv, "dynamo", "w7")
    assert got == desc
    assert await get_descriptor(kv, "dynamo", "nope") is None
    await kv.close()
    server.close()


# ---------------------------------------------------------------------------
# keystone: engine-to-engine page migration, decode continues bit-exactly


async def test_engine_kv_handoff_decode_matches(setup):
    cfg, ecfg, params = setup
    # 33 tokens = 2 complete pages + 1 tail token: the decode side can then
    # serve BOTH transferred pages from cache and compute only the tail
    prompt = list(range(1, 34))
    n_new = 12

    # reference: one engine does the whole thing locally (greedy)
    ref_eng = mk_engine(setup, "ref")
    ref = await collect(ref_eng, PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    ))
    await ref_eng.stop()

    # "prefill worker": computes KV for the prompt (1 token is enough)
    pre = mk_engine(setup, "pre")
    await collect(pre, PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=1, ignore_eos=True),
    ))
    seq = TokenBlockSequence.from_tokens(prompt, PS, salt="")
    hashes = seq.block_hashes()[:2]
    src_pages = pre.allocator.match_prefix(hashes)
    assert len(src_pages) == 2  # prompt blocks committed + matchable

    # "decode worker": receives the pages over the TCP data plane
    dec = mk_engine(setup, "dec")
    dst_pages = dec.allocator.allocate(2)
    srv = BlockTransferServer(
        read_fn=pre.export_pages, write_fn=dec.import_pages
    )
    host, port = await srv.start()

    # pull from prefill's pool, push into decode's pool — but re-indexed:
    # read src ids from the server, then write into dst ids
    data = await read_remote_pages(host, port, src_pages)
    assert data.shape == (2, cfg.num_layers, cfg.num_kv_heads, 2, PS,
                          cfg.head_dim)
    await write_remote_pages(host, port, dst_pages, data)

    # register the transferred pages in decode's prefix cache with the
    # sequence's REAL hash chain (parent = salt root for block 0) so KV
    # STORED events would carry router-consistent chaining
    for pg, blk in zip(dst_pages, seq.blocks[:2]):
        assert dec.allocator.commit(pg, blk.block_hash, blk.parent_hash)
    dec.allocator.free(dst_pages)  # hand to the cache (refcount drop)

    hits_before = dec.allocator.hit_blocks
    out = await collect(dec, PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    ))
    assert dec.allocator.hit_blocks - hits_before == 2  # prefix came via wire
    assert out == ref  # decode from transferred KV is bit-exact

    await srv.stop()
    await pre.stop()
    await dec.stop()


# ---------------------------------------------------------------------------
# chunk-pipelined streams (write_pages stream framing + eof ack)


async def test_write_pages_stream_roundtrip():
    """Multi-frame chunk stream scatters per chunk on arrival and acks
    once at eof; bytes land exactly as one monolithic write would."""
    from dynamo_tpu.kv_transfer import write_pages_stream
    from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER

    pool = {"data": np.zeros((2, 2, 1, 16, PS, 4), np.float32)}
    scattered = []

    def write_fn(pages, data):
        scattered.append(list(pages))
        pool["data"][:, :, :, pages] = data

    srv = BlockTransferServer(write_fn=write_fn)
    host, port = await srv.start()
    rng = np.random.default_rng(1)
    payload = rng.standard_normal((2, 2, 1, 6, PS, 4)).astype(np.float32)
    tx0 = KV_TRANSFER.get("dynamo_kv_transfer_tx_chunks_total")
    rx0 = KV_TRANSFER.get("dynamo_kv_transfer_rx_chunks_total")
    st0 = KV_TRANSFER.get("dynamo_kv_transfer_streams_total")
    dst = [3, 4, 5, 9, 10, 11]
    n = await write_pages_stream(host, port, [
        (dst[0:2], payload[:, :, :, 0:2]),
        (dst[2:4], payload[:, :, :, 2:4]),
        (dst[4:6], payload[:, :, :, 4:6]),
    ])
    assert n == 3
    assert scattered == [[3, 4], [5, 9], [10, 11]]
    np.testing.assert_array_equal(pool["data"][:, :, :, dst], payload)
    assert np.all(pool["data"][:, :, :, 0] == 0)
    assert KV_TRANSFER.get("dynamo_kv_transfer_tx_chunks_total") == tx0 + 3
    assert KV_TRANSFER.get("dynamo_kv_transfer_rx_chunks_total") == rx0 + 3
    assert KV_TRANSFER.get("dynamo_kv_transfer_streams_total") == st0 + 1
    await srv.stop()


async def test_write_pages_stream_error_deferred_to_eof():
    """A mid-stream scatter failure (e.g. guarded write for a cancelled
    job) is remembered, later chunks are skipped, and the SINGLE eof ack
    carries the error — the sender pipelines without per-chunk acks."""
    from dynamo_tpu.kv_transfer import (
        BlockTransferError,
        write_pages_stream,
    )

    calls = []

    def write_fn(pages, data, job_id=None):
        calls.append(list(pages))
        if 7 in pages:
            raise RuntimeError("job cancelled; write rejected")

    srv = BlockTransferServer(write_fn=write_fn)
    host, port = await srv.start()
    data = np.zeros((2, 2, 1, 2, PS, 4), np.float32)
    with pytest.raises(BlockTransferError, match="cancelled"):
        await write_pages_stream(host, port, [
            ([1, 2], data), ([7, 8], data), ([3, 4], data),
        ], job_id="j1")
    # chunk 3 was never scattered: the stream was already poisoned
    assert calls == [[1, 2], [7, 8]]
    await srv.stop()


async def test_probe_and_chunked_hash_read():
    """The G4 probe answers found WITHOUT exporting bytes; the chunked
    hash read streams the run frame by frame (on_chunk sees offsets)."""
    from dynamo_tpu.kv_transfer import (
        probe_remote_hashes,
        read_remote_hashes,
    )

    rng = np.random.default_rng(2)
    run = rng.standard_normal((2, 2, 1, 5, PS, 4)).astype(np.float32)

    def count_fn(hashes):
        return min(5, len(hashes))

    def stream_fn(hashes, chunk_pages):
        found = min(5, len(hashes))

        def gen():
            for i in range(0, found, chunk_pages):
                yield run[:, :, :, i:i + chunk_pages]

        return found, gen()

    srv = BlockTransferServer(
        count_hashes_fn=count_fn, read_hashes_stream_fn=stream_fn,
    )
    host, port = await srv.start()
    assert await probe_remote_hashes(host, port, [11, 12, 13]) == (3, None)
    assert await probe_remote_hashes(host, port, list(range(9))) == (5, None)

    # assembled whole
    found, data = await read_remote_hashes(
        host, port, list(range(8)), chunk_pages=2
    )
    assert found == 5
    np.testing.assert_array_equal(data, run)

    # incremental landing: each chunk delivered with its page offset
    seen = []
    found, data = await read_remote_hashes(
        host, port, list(range(8)), chunk_pages=2,
        on_chunk=lambda off, arr: seen.append((off, arr.shape[3])),
    )
    assert found == 5 and data is None
    assert seen == [(0, 2), (2, 2), (4, 1)]
    await srv.stop()


async def test_engine_export_stream_matches_monolithic(setup):
    """export_pages_stream / export_hash_stream reproduce exactly what
    the monolithic export paths produce — the chunk pipeline must be a
    pure transport change."""
    eng = mk_engine(setup, "wstream")
    prompt = list(range(1, 80))  # 4 complete blocks
    await collect(eng, PreprocessedRequest(
        token_ids=prompt,
        stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
    ))
    seq = TokenBlockSequence.from_tokens(prompt, PS, salt="")
    hashes = seq.block_hashes()
    pages = eng.allocator.match_prefix(hashes)
    assert len(pages) >= 4
    try:
        whole = eng.export_pages(pages)
        parts = list(eng.export_pages_stream(pages, chunk_pages=3))
        assert len(parts) == (len(pages) + 2) // 3
        np.testing.assert_array_equal(
            np.concatenate(parts, axis=3), whole
        )
    finally:
        eng.allocator.free(pages)
    found, it = eng.export_hash_stream(hashes, chunk_pages=2)
    got = list(it)
    assert found == len(pages)
    np.testing.assert_array_equal(np.concatenate(got, axis=3), whole)
    # a fully-missing run streams nothing
    found, it = eng.export_hash_stream([123456789], chunk_pages=2)
    assert found == 0 and list(it) == []
    await eng.stop()
