"""MoE models SERVED by the TpuEngine (VERDICT r3 #3: models/moe.py must
be in the serving path, not dryrun-only). The reference's analogue is the
wide-EP DeepSeek deployment (examples/sglang/dsr1-wideep.md); here the
GShard-style dense-dispatch FFN (llama._moe_ffn) rides the ordinary engine
with experts sharded over `ep` and expert hidden over `tp`."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

PS = 16


@pytest.fixture(scope="module")
def moe_setup():
    cfg = ModelConfig.tiny_moe(dtype="float32")
    ecfg = EngineConfig(
        num_pages=32, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=8):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


def test_moe_ffn_routes_to_topk_experts(moe_setup):
    """The dense-dispatch FFN matches moe_reference (no drops at high
    capacity) on the same weights."""
    cfg, _, params = moe_setup
    from dynamo_tpu.models.moe import MoEConfig, moe_reference

    lp = jax.tree.map(lambda x: x[0], params["layers"])
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(12, cfg.hidden_size), jnp.float32)
    got = llama._moe_ffn(cfg, lp, x)
    ref = moe_reference(
        x,
        {"wr": lp["wr"], "wg": lp["we_g"], "wu": lp["we_u"],
         "wd": lp["we_d"]},
        MoEConfig(hidden_size=cfg.hidden_size,
                  intermediate_size=cfg.intermediate_size,
                  num_experts=8, top_k=2),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


async def test_engine_serves_moe_e2e(moe_setup):
    """tiny_moe through the FULL TpuEngine: prefill + fused decode rounds;
    output matches the hand-driven model loop bit-exactly."""
    from tests.test_engine import manual_greedy

    cfg, ecfg, params = moe_setup
    eng = TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))
    prompt = list(range(1, 25))
    n_new = 10
    toks = await collect(eng, req_for(prompt, n_new))
    ref = manual_greedy(cfg, params, ecfg, prompt, n_new)
    assert toks == ref
    # prefix reuse works for MoE contexts too
    toks2 = await collect(eng, req_for(prompt, n_new))
    assert toks2 == ref
    assert eng.allocator.hit_blocks >= 1
    await eng.stop()


def test_moe_sharded_matches_unsharded(moe_setup):
    """ep=2 x tp=2 GSPMD execution of the MoE prefill equals single-device
    (XLA inserts the expert all_to_alls; CPU mesh)."""
    cfg, _, params = moe_setup
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = make_mesh(MeshConfig(ep=2, tp=2), jax.devices()[:4])
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s), params,
        llama.param_shardings(cfg, mesh),
    )
    ctx = llama.init_ctx(cfg, 1, 64, dtype=jnp.float32)
    ctx_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        llama.init_ctx(cfg, 1, 64, dtype=jnp.float32),
        llama.ctx_shardings(cfg, mesh),
    )
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, cfg.vocab_size, size=20).tolist()
    toks = np.zeros(32, np.int32)
    toks[: len(prompt)] = prompt
    args = (jnp.asarray(toks), jnp.int32(0), jnp.int32(0),
            jnp.int32(len(prompt)))
    _, ref = llama.prefill(cfg, params, ctx, *args)
    with mesh:
        _, got = llama.prefill(cfg, params_sh, ctx_sh, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
