"""Protocol-layer tests: validation, SSE round-trip, delta/aggregation."""
import pytest
from pydantic import ValidationError

from dynamo_tpu.protocols.aggregator import aggregate_chunks
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    DeltaGenerator,
)
from dynamo_tpu.protocols.sse import SseDecoder, encode_done, encode_event


def chat_req(**kw):
    base = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    base.update(kw)
    return ChatCompletionRequest(**base)


def test_request_validation_bounds():
    chat_req(temperature=0.7, top_p=0.9, max_tokens=10)
    with pytest.raises(ValidationError):
        chat_req(temperature=3.0)
    with pytest.raises(ValidationError):
        chat_req(max_tokens=0)
    with pytest.raises(ValidationError):
        chat_req(messages=[])
    with pytest.raises(ValidationError):
        chat_req(stop=[str(i) for i in range(9)])
    r = chat_req(stop="END", max_completion_tokens=5)
    sc = r.to_stop_conditions(default_max_tokens=99)
    assert sc.stop == ["END"] and sc.max_tokens == 5
    assert chat_req().to_stop_conditions(77).max_tokens == 77


def test_completion_request_prompt_forms():
    CompletionRequest(model="m", prompt="hello")
    CompletionRequest(model="m", prompt=[1, 2, 3])


def test_sse_roundtrip():
    dec = SseDecoder()
    chunks = [encode_event({"i": i}) for i in range(3)] + [encode_done()]
    blob = b"".join(chunks)
    # feed in awkward byte splits
    events = []
    for i in range(0, len(blob), 7):
        events.extend(dec.feed(blob[i : i + 7]))
    assert [e.json()["i"] for e in events[:3]] == [0, 1, 2]
    assert events[3].is_done


def test_delta_generator_and_aggregate():
    gen = DeltaGenerator("mymodel", chat=True)
    chunks = [
        gen.text_chunk("Hel"),
        gen.text_chunk("lo"),
        gen.finish_chunk(FinishReason.EOS),
        gen.usage_chunk(5, 2),
    ]
    # role only on first delta
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    assert "role" not in chunks[1]["choices"][0]["delta"]
    final = aggregate_chunks(chunks)
    assert final["object"] == "chat.completion"
    assert final["choices"][0]["message"]["content"] == "Hello"
    assert final["choices"][0]["finish_reason"] == "stop"
    assert final["usage"]["total_tokens"] == 7


def test_completion_delta_aggregate():
    gen = DeltaGenerator("m", chat=False)
    final = aggregate_chunks(
        [gen.text_chunk("a"), gen.text_chunk("b"), gen.finish_chunk(FinishReason.LENGTH)]
    )
    assert final["object"] == "text_completion"
    assert final["choices"][0]["text"] == "ab"
    assert final["choices"][0]["finish_reason"] == "length"
