"""Embeddings API tests (reference protocols/openai embeddings surface)."""
import asyncio
import base64
import struct

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.backend import Backend
from dynamo_tpu.engines import EchoEngine
from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.tokenizer import make_test_tokenizer

WORDS = [f"w{i}" for i in range(100)]


def test_encode_padding_invariant():
    import jax.numpy as jnp

    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    prompt = list(range(1, 12))

    def run(pad_to):
        toks = np.zeros(pad_to, np.int32)
        toks[: len(prompt)] = prompt
        return np.asarray(llama.encode(
            cfg, params, jnp.asarray(toks), jnp.int32(len(prompt))
        ))

    a, b = run(16), run(32)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert abs(float(np.linalg.norm(a)) - 1.0) < 1e-5
    # different prompt -> different vector
    toks = np.zeros(16, np.int32)
    toks[:5] = [9, 8, 7, 6, 5]
    import jax.numpy as jnp2

    c = np.asarray(llama.encode(cfg, params, jnp2.asarray(toks),
                                jnp2.int32(5)))
    assert not np.allclose(a, c)


async def test_tpu_engine_embed_while_serving():
    import jax.numpy as jnp  # noqa: F401

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )

    cfg = ModelConfig.tiny(dtype="float32")
    eng = TpuEngine(
        cfg,
        EngineConfig(num_pages=32, page_size=16, max_pages_per_seq=8,
                     max_decode_slots=2, prefill_buckets=(32,),
                     cache_dtype="float32"),
        params=llama.init_params(cfg, 0),
        mesh_config=MeshConfig(tp=1),
    )

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(range(1, 20)),
            stop_conditions=StopConditions(max_tokens=10, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        return toks

    gen_task = asyncio.create_task(gen())
    v1 = await asyncio.to_thread(eng.embed, [1, 2, 3, 4, 5])
    v2 = await asyncio.to_thread(eng.embed, [1, 2, 3, 4, 5])
    toks = await gen_task
    assert len(toks) == 10
    assert v1 == v2 and len(v1) == cfg.hidden_size
    assert abs(sum(x * x for x in v1) - 1.0) < 1e-4
    await eng.stop()


def make_service():
    tok = make_test_tokenizer(WORDS)
    chain = ModelChain(
        name="emb",
        preprocessor=OpenAIPreprocessor(
            tokenizer=tok, formatter=PromptFormatter(), model_name="emb"
        ),
        engine=EchoEngine(delay_s=0.0),
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    return HttpService(manager)


async def test_http_embeddings():
    svc = make_service()
    client = TestClient(TestServer(svc.app))
    await client.start_server()

    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": "w1 w2 w3",
    })
    assert r.status == 200
    body = await r.json()
    assert body["object"] == "list" and len(body["data"]) == 1
    v = body["data"][0]["embedding"]
    assert len(v) == 16 and abs(sum(x * x for x in v) - 1.0) < 1e-6
    assert body["usage"]["prompt_tokens"] == 3

    # batch input preserves order/index
    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": ["w1 w2", "w3 w4 w5"],
    })
    body = await r.json()
    assert [d["index"] for d in body["data"]] == [0, 1]
    assert body["data"][0]["embedding"] != body["data"][1]["embedding"]

    # base64 encoding round-trips
    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": "w1 w2", "encoding_format": "base64",
    })
    blob = (await r.json())["data"][0]["embedding"]
    decoded = struct.unpack("<16f", base64.b64decode(blob))
    assert abs(sum(x * x for x in decoded) - 1.0) < 1e-5

    # pre-tokenized input
    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": [3, 4, 5],
    })
    assert r.status == 200

    # error paths
    r = await client.post("/v1/embeddings", json={
        "model": "nope", "input": "x",
    })
    assert r.status == 404
    r = await client.post("/v1/embeddings", json={"model": "emb",
                                                  "input": ""})
    assert r.status == 400
    await client.close()


async def test_http_embeddings_dimensions_and_empty_list():
    svc = make_service()
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": "w1 w2 w3", "dimensions": 8,
    })
    v = (await r.json())["data"][0]["embedding"]
    assert len(v) == 8
    assert abs(sum(x * x for x in v) - 1.0) < 1e-6  # re-normalized
    r = await client.post("/v1/embeddings", json={
        "model": "emb", "input": [],
    })
    assert r.status == 400
    await client.close()
