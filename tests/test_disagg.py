"""Disaggregated prefill/decode tests (reference disagg_router.rs,
prefill_worker.py, utils/prefill_queue.py — SURVEY §3.3 flow).

Keystone: frontend-shaped request -> decode engine decides remote -> job on
the durable prefill queue -> prefill worker computes KV + pushes pages over
the block-transfer plane into the decode pool -> decode continues from the
transferred prefix bit-exactly, computing only the sub-page tail.
"""
import asyncio
from dataclasses import replace

import pytest

from dynamo_tpu.disagg import (
    DisaggConfig,
    DisaggConfigWatcher,
    DisaggDecodeEngine,
    PrefillWorker,
    prefill_queue_name,
    set_disagg_config,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_transfer import (
    BlocksetDescriptor,
    BlockTransferServer,
    KvCacheLayout,
    publish_descriptor,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import serve_store

PS = 16


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def mk_engine(setup, wid, **over):
    cfg, ecfg, params = setup
    return TpuEngine(
        cfg, replace(ecfg, worker_id=wid, **over), params=params,
        mesh_config=MeshConfig(tp=1),
    )


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=10):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


async def start_rt():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    return server, store, rt, port


async def test_disagg_config_watch():
    server, store, rt, port = await start_rt()
    w = await DisaggConfigWatcher(rt.kv, "ns").start()
    assert w.current == DisaggConfig()  # defaults before any put
    await set_disagg_config(rt.kv, "ns", DisaggConfig(
        max_local_prefill_length=99, max_prefill_queue_size=3))
    for _ in range(100):
        if w.current.max_local_prefill_length == 99:
            break
        await asyncio.sleep(0.02)
    assert w.current.max_prefill_queue_size == 3
    await w.stop()
    await rt.close()
    server.close()


async def setup_disagg_pair(setup, rt, namespace="dynamo",
                            prefill_timeout_s=30.0,
                            prefill_chunk_pages=None,
                            wid="dec", pwid="pre"):
    """decode engine + data plane + descriptor + prefill worker.
    ``prefill_chunk_pages`` overrides the prefill engine's
    kv_transfer_chunk_pages (0 = monolithic legacy path)."""
    decode_inner = mk_engine(setup, wid)
    cfg, ecfg, _ = setup
    conf = DisaggConfigWatcher(
        rt.kv, namespace,
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=4),
    )
    await conf.start()
    decode = DisaggDecodeEngine(
        decode_inner, rt, namespace=namespace, worker_id=wid,
        conf=conf, prefill_timeout_s=prefill_timeout_s,
    )
    srv = BlockTransferServer(
        read_fn=decode_inner.export_pages, write_fn=decode.guarded_import
    )
    host, port = await srv.start()
    await publish_descriptor(rt.kv, namespace, BlocksetDescriptor(
        worker_id=wid, host=host, port=port,
        layout=KvCacheLayout(cfg.num_layers, cfg.num_kv_heads, PS,
                             cfg.head_dim, "float32"),
    ))
    over = ({}
            if prefill_chunk_pages is None
            else {"kv_transfer_chunk_pages": prefill_chunk_pages})
    prefill_engine = mk_engine(setup, pwid, **over)
    pworker = await PrefillWorker(
        rt, prefill_engine, namespace=namespace, poll_timeout_s=0.2
    ).start()
    return decode, srv, conf, pworker, prefill_engine


async def test_disagg_remote_prefill_e2e(setup):
    """Long prompt goes through the queue + prefill worker + KV transfer;
    output is bit-exact vs a purely local engine."""
    prompt = list(range(1, 50))  # 49 tokens: 3 complete blocks + tail

    ref_eng = mk_engine(setup, "ref")
    ref = await collect(ref_eng, req_for(prompt))
    await ref_eng.stop()

    server, store, rt, port = await start_rt()
    decode, srv, conf, pworker, pre_eng = await setup_disagg_pair(setup, rt)

    out = await collect(decode, req_for(prompt))
    assert out == ref
    assert decode.remote_prefills == 1
    assert decode.remote_fallbacks == 0
    assert pworker.jobs_handled == 1
    # the decode engine served the transferred blocks from its prefix cache
    assert decode.engine.allocator.hit_blocks >= 3

    # short prompt stays local
    short = await collect(decode, req_for(list(range(1, 10))))
    assert len(short) == 10
    assert decode.local_prefills >= 1

    await pworker.stop()
    await srv.stop()
    await conf.stop()
    await decode.stop()
    await pre_eng.stop()
    await rt.close()
    server.close()


async def test_disagg_remote_prefill_spans_ride_finishing_output(setup):
    """Telemetry satellite: the remote path annotates the finishing
    output with the decode-side disagg_kv_transfer span AND the prefill
    worker's own remote_prefill span (shipped back on the done queue) —
    the remote hop is visible end-to-end in the request's trace tree."""
    prompt = list(range(1, 50))
    server, store, rt, port = await start_rt()
    decode, srv, conf, pworker, pre_eng = await setup_disagg_pair(setup, rt)
    try:
        finishing = None
        async for out in decode.generate(req_for(prompt)):
            if out.finish_reason is not None:
                finishing = out
        assert decode.remote_prefills == 1
        spans = (finishing.annotations.get("trace") or {}).get("spans", [])
        names = [s.get("name") for s in spans]
        assert "disagg_kv_transfer" in names
        assert "remote_prefill" in names
        rp = next(s for s in spans if s["name"] == "remote_prefill")
        assert rp["attrs"]["tokens"] == len(prompt)
        assert rp["attrs"]["blocks"] >= 3
        # the engine's own queue/prefill spans are still there
        assert "prefill" in names
    finally:
        await pworker.stop()
        await srv.stop()
        await conf.stop()
        await decode.stop()
        await pre_eng.stop()
        await rt.close()
        server.close()


async def test_disagg_fallback_and_stale_job_write_rejected(setup):
    """No prefill worker at first: decode falls back locally after the
    timeout. When a worker later pops the STALE job, its write must be
    rejected (the fallback freed those pages — they may belong to another
    request by now), not scatter into the decode pool."""
    cfg, ecfg, _ = setup
    prompt = list(range(1, 50))
    ref_eng = mk_engine(setup, "ref2")
    ref = await collect(ref_eng, req_for(prompt))
    await ref_eng.stop()

    server, store, rt, port = await start_rt()
    decode_inner = mk_engine(setup, "dec2")
    conf = DisaggConfigWatcher(
        rt.kv, "dynamo",
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=4),
    )
    decode = DisaggDecodeEngine(
        decode_inner, rt, worker_id="dec2", conf=conf,
        prefill_timeout_s=0.3,
    )
    srv = BlockTransferServer(
        read_fn=decode_inner.export_pages, write_fn=decode.guarded_import
    )
    host, xport = await srv.start()
    await publish_descriptor(rt.kv, "dynamo", BlocksetDescriptor(
        worker_id="dec2", host=host, port=xport,
        layout=KvCacheLayout(cfg.num_layers, cfg.num_kv_heads, PS,
                             cfg.head_dim, "float32"),
    ))

    out = await collect(decode, req_for(prompt))
    assert out == ref
    assert decode.remote_fallbacks == 1
    # the abandoned job is still on the durable queue (no consumer yet)
    assert await rt.kv.qlen(prefill_queue_name("dynamo")) == 1

    # a late prefill worker pops the stale job: it is EXPIRED (decode gave
    # up at its timeout), so the worker drops it without a wasted prefill
    # or a done-queue push, and decode keeps serving correctly
    pre_eng = mk_engine(setup, "pre2")
    pworker = PrefillWorker(rt, pre_eng, namespace="dynamo",
                            poll_timeout_s=0.2)
    pworker.expiry_skew_s = 0.0  # same host: no clock skew
    await pworker.start()
    for _ in range(300):
        if (pworker.jobs_expired + pworker.jobs_failed
                + pworker.jobs_handled) >= 1:
            break
        await asyncio.sleep(0.05)
    assert pworker.jobs_expired == 1
    assert pworker.jobs_failed == 0 and pworker.jobs_handled == 0
    out2 = await collect(decode, req_for(list(range(200, 220))))
    assert len(out2) == 10

    # stale-write protection itself: a write for a cancelled/unknown job id
    # is rejected outright
    with pytest.raises(RuntimeError, match="cancelled"):
        decode.guarded_import([1], None, job_id="long-gone")

    await pworker.stop()
    await pre_eng.stop()
    await srv.stop()
    await decode.stop()
    await rt.close()
    server.close()


async def test_disagg_decision_respects_queue_cap(setup):
    """queue >= max_prefill_queue_size forces the local path."""
    server, store, rt, port = await start_rt()
    # stuff the queue past the cap
    q = prefill_queue_name("dynamo")
    await rt.kv.qpush(q, "{}")
    await rt.kv.qpush(q, "{}")
    decode_inner = mk_engine(setup, "dec3")
    conf = DisaggConfigWatcher(
        rt.kv, "dynamo",
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=2),
    )
    decode = DisaggDecodeEngine(
        decode_inner, rt, worker_id="dec3", conf=conf,
    )
    out = await collect(decode, req_for(list(range(1, 50))))
    assert len(out) == 10
    assert decode.remote_prefills == 0
    assert decode.local_prefills == 1
    assert await rt.kv.qlen(q) == 2  # nothing enqueued
    await decode.stop()
    await rt.close()
    server.close()


async def test_disagg_through_distributed_stack(setup):
    """Full stack: decode worker registered over the runtime (register_llm),
    request arrives via the remote endpoint client, remote prefill rides
    the queue + transfer plane (the SURVEY §3.3 S1-S13 flow on CPU)."""
    from dynamo_tpu.frontend.watcher import ModelEntry, register_llm
    from dynamo_tpu.runtime.remote_engine import RemoteEngine

    prompt = list(range(1, 50))
    ref_eng = mk_engine(setup, "ref3")
    ref = await collect(ref_eng, req_for(prompt))
    await ref_eng.stop()

    server, store, rt, port = await start_rt()
    cfg, ecfg, _ = setup

    # decode worker: disagg wrapper registered as the model engine
    decode_inner = mk_engine(setup, "dec4")
    conf = await DisaggConfigWatcher(
        rt.kv, "test",
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=4),
    ).start()
    decode = DisaggDecodeEngine(
        decode_inner, rt, namespace="test", conf=conf,
        prefill_timeout_s=30.0,
    )
    entry = ModelEntry(name="m", namespace="test", component="backend",
                       block_size=PS, router_mode="kv")
    served = await register_llm(rt, decode, entry)
    decode.worker_id = str(served.lease_id)
    srv = BlockTransferServer(
        read_fn=decode_inner.export_pages, write_fn=decode.guarded_import
    )
    host, xport = await srv.start()
    await publish_descriptor(rt.kv, "test", BlocksetDescriptor(
        worker_id=str(served.lease_id), host=host, port=xport,
        layout=KvCacheLayout(cfg.num_layers, cfg.num_kv_heads, PS,
                             cfg.head_dim, "float32"),
    ))

    # prefill worker on its own runtime connection
    rt2 = await DistributedRuntime.connect(port=port)
    pre_eng = mk_engine(setup, "pre4")
    pworker = await PrefillWorker(
        rt2, pre_eng, namespace="test", poll_timeout_s=0.2
    ).start()

    # request through the distributed data plane
    client = await rt.namespace("test").component("backend").endpoint(
        "generate"
    ).client()
    await client.wait_for_instances(1)
    remote = RemoteEngine(client)
    out = await collect(remote, req_for(prompt))
    assert out == ref
    assert decode.remote_prefills == 1
    assert pworker.jobs_handled == 1

    await client.stop()
    await pworker.stop()
    await srv.stop()
    await conf.stop()
    await served.shutdown()
    await decode.stop()
    await pre_eng.stop()
    await rt2.close()
    await rt.close()
    server.close()


async def test_disagg_chunked_stream_greedy_differential(setup):
    """Tier-1 keystone for the chunk pipeline: chunk-streamed remote
    prefill is greedy byte-identical to the monolithic transfer (same
    113-token prompt through both data planes — the transport change
    must be invisible) AND to pure-local prefill (49-token prompt, the
    shape the e2e tests pin local equality at; longer prompts flip
    near-tie argmaxes on the tiny random model because a prefix-hit
    tail prefill computes its boundary KV in a different padded shape —
    a pre-existing float quirk, not a transfer property). Also: the
    stream really was multi-frame, and the remote_prefill span carries
    per-chunk children."""
    prompt = list(range(1, 114))       # 7 complete blocks + tail
    p49 = list(range(200, 249))        # 3 complete blocks + tail

    ref_eng = mk_engine(setup, "refc")
    ref49 = await collect(ref_eng, req_for(p49))
    await ref_eng.stop()

    server, store, rt, port = await start_rt()
    from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER

    streams0 = KV_TRANSFER.get("dynamo_kv_transfer_streams_total")

    # chunk-streamed pair (2 pages per chunk -> >= 3 frames)
    decode_c, srv_c, conf_c, pw_c, pre_c = await setup_disagg_pair(
        setup, rt, namespace="chunked", prefill_chunk_pages=2,
        wid="dec_c", pwid="pre_c",
    )
    # monolithic pair (legacy single-blob path)
    decode_m, srv_m, conf_m, pw_m, pre_m = await setup_disagg_pair(
        setup, rt, namespace="mono", prefill_chunk_pages=0,
        wid="dec_m", pwid="pre_m",
    )
    try:
        finishing = None
        out_c = []
        async for out in decode_c.generate(req_for(prompt)):
            out_c.extend(out.token_ids)
            if out.finish_reason is not None:
                finishing = out
        chunks_113 = decode_c.last_transfer_chunks
        out_m = await collect(decode_m, req_for(prompt))

        # chunked vs monolithic: same bytes, same decode -> identical
        assert out_c == out_m
        # chunked remote vs pure-local prefill: identical
        out_c49 = await collect(decode_c, req_for(p49))
        assert out_c49 == ref49
        assert decode_c.remote_prefills == 2
        assert decode_c.remote_fallbacks == 0
        assert decode_m.remote_prefills == 1
        # the chunked path really streamed multiple frames...
        assert pw_c.chunks_streamed >= 3
        assert chunks_113 >= 3
        assert pw_c.transfer_overlap_ratio is not None
        assert KV_TRANSFER.get(
            "dynamo_kv_transfer_streams_total") > streams0
        # ...and the monolithic one did not
        assert pw_m.chunks_streamed == 0
        # per-chunk child spans under remote_prefill
        spans = (finishing.annotations.get("trace") or {}).get("spans", [])
        rp = next(s for s in spans if s.get("name") == "remote_prefill")
        kids = rp.get("children", [])
        assert len(kids) >= 3
        assert all(k["name"] == "kv_chunk" for k in kids)
        assert sum(k["attrs"]["blocks"] for k in kids) == rp["attrs"]["blocks"]
    finally:
        for pw, srv, conf, dec, pre in (
            (pw_c, srv_c, conf_c, decode_c, pre_c),
            (pw_m, srv_m, conf_m, decode_m, pre_m),
        ):
            await pw.stop()
            await srv.stop()
            await conf.stop()
            await dec.stop()
            await pre.stop()
        await rt.close()
        server.close()


@pytest.mark.slow
async def test_disagg_chunked_chaos_stall_falls_back(setup):
    """Full-stack chunked remote prefill with a mid-stream stall_stream
    chaos fault: the decode side's timeout fires, it falls back to LOCAL
    prefill (token-identical output, fallback counted on the metrics
    plane), and the stale stream's late writes are rejected by the
    guarded import instead of scribbling on reallocated pages."""
    from dynamo_tpu.frontend.watcher import ModelEntry, register_llm
    from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER
    from dynamo_tpu.resilience.chaos import CHAOS
    from dynamo_tpu.runtime.remote_engine import RemoteEngine

    prompt = list(range(1, 114))
    ref_eng = mk_engine(setup, "refs")
    ref = await collect(ref_eng, req_for(prompt))
    await ref_eng.stop()

    server, store, rt, port = await start_rt()
    cfg, ecfg, _ = setup
    decode_inner = mk_engine(setup, "dec_st")
    conf = await DisaggConfigWatcher(
        rt.kv, "stall",
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=4),
    ).start()
    decode = DisaggDecodeEngine(
        decode_inner, rt, namespace="stall", conf=conf,
        prefill_timeout_s=1.0,
    )
    entry = ModelEntry(name="m", namespace="stall", component="backend",
                       block_size=PS, router_mode="kv")
    served = await register_llm(rt, decode, entry)
    decode.worker_id = str(served.lease_id)
    srv = BlockTransferServer(
        read_fn=decode_inner.export_pages, write_fn=decode.guarded_import
    )
    host, xport = await srv.start()
    await publish_descriptor(rt.kv, "stall", BlocksetDescriptor(
        worker_id=str(served.lease_id), host=host, port=xport,
        layout=KvCacheLayout(cfg.num_layers, cfg.num_kv_heads, PS,
                             cfg.head_dim, "float32"),
    ))
    rt2 = await DistributedRuntime.connect(port=port)
    pre_eng = mk_engine(setup, "pre_st", kv_transfer_chunk_pages=2)
    pworker = await PrefillWorker(
        rt2, pre_eng, namespace="stall", poll_timeout_s=0.2
    ).start()
    fb0 = KV_TRANSFER.get("dynamo_disagg_fallback_total")
    # stall the stream for longer than the decode side's 1.0 s timeout,
    # after the first chunk frame went out (mid-stream, not pre-stream)
    CHAOS.arm("stall_stream", delay_s=4.0, after_outputs=1, once=True)
    try:
        client = await rt.namespace("stall").component("backend").endpoint(
            "generate"
        ).client()
        await client.wait_for_instances(1)
        remote = RemoteEngine(client)
        out = await collect(remote, req_for(prompt))
        assert out == ref  # local fallback is token-identical
        assert decode.remote_fallbacks == 1
        assert decode.remote_prefills == 0
        assert KV_TRANSFER.get("dynamo_disagg_fallback_total") == fb0 + 1
        assert CHAOS.points["stall_stream"].injected_total == 1
        # the worker's stalled job must FAIL at commit (late writes for
        # the cancelled job are rejected by the guarded import)
        for _ in range(200):
            if pworker.jobs_failed + pworker.jobs_handled >= 1:
                break
            await asyncio.sleep(0.05)
        assert pworker.jobs_failed == 1
        assert pworker.jobs_handled == 0
        # decode keeps serving normally afterwards
        out2 = await collect(remote, req_for(list(range(300, 320))))
        assert len(out2) == 10
        await client.stop()
    finally:
        CHAOS.reset()
        await pworker.stop()
        await srv.stop()
        await conf.stop()
        await served.shutdown()
        await decode.stop()
        await pre_eng.stop()
        await rt2.close()
        await rt.close()
        server.close()
