"""Load predictors (reference load_predictor.py:159) + SLA interpolation
(reference utils/perf_interpolation.py) + planner-with-predictor sim."""
from __future__ import annotations

import numpy as np
import pytest

from dynamo_tpu.predictors import (
    ARPredictor,
    ConstantPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from dynamo_tpu.profiler import SlaCapacity


def test_constant_returns_last():
    p = ConstantPredictor()
    assert p.predict_next() == 0.0
    for v in (3, 7, 5):
        p.add_data_point(v)
    assert p.predict_next() == 5.0
    assert p.get_last_value() == 5.0


def test_moving_average_smooths():
    p = MovingAveragePredictor(window_size=4)
    for v in (0, 10, 0, 10):
        p.add_data_point(v)
    assert p.predict_next() == pytest.approx(5.0)


def test_ar_learns_linear_trend():
    p = ARPredictor(window_size=30, order=3, d=1)
    for i in range(20):
        p.add_data_point(2.0 * i)
    # next value of 0,2,4,... is 40
    assert p.predict_next() == pytest.approx(40.0, abs=1.0)


def test_ar_constant_series():
    p = ARPredictor()
    for _ in range(15):
        p.add_data_point(7.0)
    assert p.predict_next() == pytest.approx(7.0, abs=0.5)


def test_ar_never_negative():
    p = ARPredictor(d=1)
    for v in (50, 40, 30, 20, 10, 5, 2, 1, 0, 0):
        p.add_data_point(v)
    assert p.predict_next() >= 0.0


def test_ar_few_points_falls_back_to_mean():
    p = ARPredictor()
    p.add_data_point(4.0)
    p.add_data_point(6.0)
    assert p.predict_next() == pytest.approx(5.0)


def test_nan_observation_ignored():
    p = ConstantPredictor()
    p.add_data_point(3.0)
    p.add_data_point(float("nan"))
    assert p.predict_next() == 3.0


def test_make_predictor_names():
    assert isinstance(make_predictor("constant"), ConstantPredictor)
    assert isinstance(make_predictor("arima"), ARPredictor)
    with pytest.raises(ValueError):
        make_predictor("nonesuch")


# ---------------------------------------------------------------------------
# SLA surface interpolation

def _profile(points):
    return {"configs": [{"name": "c", "points": points}]}


def test_interpolate_between_points():
    cap = SlaCapacity(
        profile=_profile([
            {"concurrency": 2, "ttft_p50_s": 0.1, "itl_p50_s": 0.01},
            {"concurrency": 10, "ttft_p50_s": 0.9, "itl_p50_s": 0.05},
        ]),
        ttft_sla_s=0.5,
    )
    ttft, itl = cap.interpolate(6.0)
    assert ttft == pytest.approx(0.5)
    assert itl == pytest.approx(0.03)
    # clamped outside range
    assert cap.interpolate(1)[0] == pytest.approx(0.1)
    assert cap.interpolate(99)[0] == pytest.approx(0.9)


def test_max_concurrency_interpolates_crossing():
    cap = SlaCapacity(
        profile=_profile([
            {"concurrency": 2, "ttft_p50_s": 0.1, "itl_p50_s": 0.01},
            {"concurrency": 10, "ttft_p50_s": 0.9, "itl_p50_s": 0.05},
        ]),
        ttft_sla_s=0.5,
    )
    # crossing at concurrency 6 — between the profiled 2 and 10
    assert cap.max_concurrency() == 6


def test_max_concurrency_zero_when_even_lowest_violates():
    cap = SlaCapacity(
        profile=_profile([{"concurrency": 1, "ttft_p50_s": 2.0,
                           "itl_p50_s": 0.5}]),
        ttft_sla_s=0.5,
    )
    assert cap.max_concurrency() == 0


def test_max_concurrency_full_range_ok():
    cap = SlaCapacity(
        profile=_profile([
            {"concurrency": 1, "ttft_p50_s": 0.1, "itl_p50_s": 0.01},
            {"concurrency": 8, "ttft_p50_s": 0.2, "itl_p50_s": 0.02},
        ]),
        ttft_sla_s=0.5, itl_sla_s=0.1,
    )
    assert cap.max_concurrency() == 8


# ---------------------------------------------------------------------------
# planner sim: predictor-filtered decisions flap less on noisy load

class _FakeConnector:
    def __init__(self):
        self.n = 2

    def current_replicas(self) -> int:
        return self.n

    async def set_replicas(self, n: int) -> None:
        self.n = n


def _sim_flaps(predictor: str, series) -> int:
    """Feed a load series through Planner.decide(); count target changes."""
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats,
    )
    from dynamo_tpu.planner import Planner, PlannerConfig

    cfg = PlannerConfig(predictor=predictor, stable_intervals=1,
                        min_replicas=1, max_replicas=8)
    conn = _FakeConnector()
    planner = Planner(kv=None, connector=conn, config=cfg)
    changes = 0
    prev = conn.n
    for usage in series:
        planner.aggregator.update(ForwardPassMetrics(
            worker_id="w0",
            worker_stats=WorkerStats(
                request_active_slots=1, request_total_slots=8,
                num_requests_waiting=0),
            kv_stats=KvStats(kv_active_blocks=int(usage * 100),
                             kv_total_blocks=100,
                             gpu_cache_usage_perc=usage),
        ))
        target = planner.decide()
        conn.n = target
        if target != prev:
            changes += 1
        prev = target
    return changes


def test_predictor_reduces_flapping():
    # noise oscillating across the scale-up threshold (0.8)
    rng = np.random.RandomState(3)
    series = np.clip(0.78 + 0.1 * rng.randn(40), 0.0, 1.0)
    flappy = _sim_flaps("constant", series)
    smooth = _sim_flaps("moving_average", series)
    assert smooth < flappy


def test_seasonal_predictor_tracks_cycles():
    """The Prophet-slot predictor (reference load_predictor.py:159):
    after two observed cycles of a square wave, the forecast for the
    next bucket reflects that bucket's USUAL level, not the current one
    — the planner scales ahead of the daily peak."""
    import numpy as np

    from dynamo_tpu.predictors import SeasonalPredictor, make_predictor

    p = make_predictor("prophet", period=8)
    assert isinstance(p, SeasonalPredictor)
    wave = [10.0] * 4 + [100.0] * 4
    for _ in range(4):
        for v in wave:
            p.add_data_point(v)
    # next phase is the start of the low half
    low = p.predict_next()
    assert low < 50.0
    # advance into the high half: forecast jumps ahead of the data
    for v in [10.0] * 4 + [100.0] * 3:
        p.add_data_point(v)
    high = p.predict_next()
    assert high > 50.0
    assert p.predict_next() >= 0.0


def test_seasonal_predictor_prefull_cycle_is_trend_following():
    from dynamo_tpu.predictors import SeasonalPredictor

    p = SeasonalPredictor(period=100)
    for v in (1.0, 2.0, 3.0, 4.0):
        p.add_data_point(v)
    assert p.predict_next() > 3.0  # rising trend, no cycle seen yet
    p2 = SeasonalPredictor(period=10)
    assert p2.predict_next() == 0.0
