"""StoreSession resync tests (PR 15 tentpole, layer 2).

A StoreSession duck-types KvClient but survives control-plane outages:
it reconnects with backoff, reclaims (journal) or re-grants (fresh
store) its leases, re-puts lease-bound registration keys, re-establishes
watches/subscriptions, and synthesizes put/delete deltas for state that
changed while it was down. ``Lease.lost`` is consumed by the session —
a recoverable outage never surfaces it to the owner.
"""
import asyncio

import pytest

from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.session import StoreSession
from dynamo_tpu.runtime.store import KvStore, crash_store, serve_store


async def _start(port=0, **kw):
    server, store = await serve_store(port=port, sweep_interval_s=0.05, **kw)
    return server, store, server.sockets[0].getsockname()[1]


async def _wait_resynced(sess, n=1, rounds=400):
    for _ in range(rounds):
        if not sess.degraded and sess.resyncs >= n:
            return True
        await asyncio.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# re-watch delta synthesis


async def test_rewatch_synthesizes_put_and_delete_deltas():
    server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    try:
        await sess.put("p/stays", "same")
        await sess.put("p/dies", "old")
        await sess.put("p/changes", "v1")
        watch = await sess.watch_prefix("p/")
        assert {k for k, _, _ in watch.initial} == {
            "p/stays", "p/dies", "p/changes"}

        crash_store(server)
        await asyncio.sleep(0.05)
        # the replacement store saw writes while the session was down
        s2 = KvStore()
        s2.put("p/stays", "same")
        s2.put("p/changes", "v2")
        s2.put("p/born", "new")
        server2, _, _ = await _start(port=port, store=s2)
        assert await _wait_resynced(sess)

        # synthesized deltas: delete for p/dies, puts for the changed and
        # new keys, NOTHING for the unchanged key
        events = []
        for _ in range(3):
            events.append(await asyncio.wait_for(
                watch.queue.get(), timeout=2.0))
        got = {(e["event"], e["key"]) for e in events}
        assert got == {("delete", "p/dies"), ("put", "p/changes"),
                       ("put", "p/born")}
        assert all(e.get("synthetic") for e in events)
        assert {e["key"]: e.get("value")
                for e in events if e["event"] == "put"} == {
            "p/changes": "v2", "p/born": "new"}
        assert watch.queue.empty()

        # the re-established watch is LIVE on the new store
        s2.put("p/after", "x")
        ev = await asyncio.wait_for(watch.queue.get(), timeout=2.0)
        assert (ev["event"], ev["key"]) == ("put", "p/after")
        assert not ev.get("synthetic")
    finally:
        await sess.close()
        server2.close()


async def test_rewatch_no_change_synthesizes_nothing():
    jp_server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    try:
        await sess.put("p/a", "1")
        watch = await sess.watch_prefix("p/")
        crash_store(jp_server)
        await asyncio.sleep(0.05)
        s2 = KvStore()
        s2.put("p/a", "1")
        server2, _, _ = await _start(port=port, store=s2)
        assert await _wait_resynced(sess)
        assert watch.synthesized_events == 0
        assert watch.queue.empty()
    finally:
        await sess.close()
        server2.close()


# ---------------------------------------------------------------------------
# lease reclaim / re-grant


async def test_journaled_restart_reclaims_same_lease(tmp_path):
    jp = str(tmp_path / "store.wal")
    server, store, port = await _start(journal_path=jp)
    sess = await StoreSession(port=port).connect()
    try:
        lease = await sess.lease_grant(0.6)
        old_id = lease.id
        key = f"dynamo://t/_components/c/e/{old_id}"
        await sess.put(key, "reg", lease=old_id)

        crash_store(server)
        await asyncio.sleep(0.1)
        server2, store2, _ = await _start(port=port, journal_path=jp)
        assert await _wait_resynced(sess)

        # journal replay + grace window -> the SAME lease was reclaimed:
        # no registration churn, the key survived replay
        assert lease.id == old_id
        assert store2.replayed_keys == 1
        assert (await sess.get(key)) == "reg"
        assert not lease.lost.is_set()
        # keepalives flow on the new connection: the key outlives the TTL
        await asyncio.sleep(1.0)
        assert (await sess.get(key)) == "reg"
    finally:
        await sess.close()
        server2.close()
        store2.close_journal()


async def test_fresh_restart_regrants_and_reputs_keys():
    server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    try:
        lease = await sess.lease_grant(0.6)
        old_id = lease.id
        await sess.put(f"dynamo://t/_components/c/e/{old_id}", "reg",
                       lease=old_id)
        rekeys = []
        lease.on_rekey.append(lambda o, n: rekeys.append((o, n)))

        crash_store(server)
        await asyncio.sleep(0.1)
        server2, store2, _ = await _start(port=port)  # EMPTY store
        assert await _wait_resynced(sess)

        # a fresh store can re-issue a colliding id — don't assert
        # inequality; assert the INVARIANT: exactly one registration key,
        # bound to the session's current lease, value intact
        regs = await sess.get_prefix("dynamo://t/_components/c/e/")
        assert [(k, v) for k, v, _ in regs] == [
            (f"dynamo://t/_components/c/e/{lease.id}", "reg")]
        if lease.id != old_id:
            assert rekeys == [(old_id, lease.id)]
        assert not lease.lost.is_set()
        # the re-granted lease is live server-side: revoking it through
        # the session deletes the re-put key
        await sess.lease_revoke(lease.id)
        assert await sess.get_prefix("dynamo://t/_components/c/e/") == []
    finally:
        await sess.close()
        server2.close()


async def test_server_side_lease_loss_regrants_while_connected():
    """Lease.lost is actionable (satellite c): if the server expires the
    lease while the CONNECTION is healthy, the session re-grants and
    re-puts instead of leaving the worker silently deregistered."""
    server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    try:
        lease = await sess.lease_grant(0.3)
        key = f"dynamo://t/_components/c/e/{lease.id}"
        await sess.put(key, "reg", lease=lease.id)
        # authoritative server-side loss: next keepalive answers False
        store.lease_revoke(lease.id)
        assert store.get(key) is None
        for _ in range(200):
            regs = await sess.get_prefix("dynamo://t/_components/c/e/")
            if regs:
                break
            await asyncio.sleep(0.02)
        assert [(k, v) for k, v, _ in regs] == [
            (f"dynamo://t/_components/c/e/{lease.id}", "reg")]
    finally:
        await sess.close()
        server.close()


# ---------------------------------------------------------------------------
# degraded-state plumbing + client close


async def test_state_listener_sees_degraded_window():
    server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    states = []
    sess.add_state_listener(states.append)
    try:
        assert states == [False]  # fires immediately with current state
        crash_store(server)
        for _ in range(200):
            if sess.degraded:
                break
            await asyncio.sleep(0.02)
        assert states[-1] is True
        server2, _, _ = await _start(port=port)
        assert await _wait_resynced(sess)
        assert states[-1] is False
    finally:
        await sess.close()
        server2.close()


async def test_kvclient_close_awaits_writer_teardown():
    server, store, port = await _start()
    c = await KvClient(port=port).connect()
    await c.put("k", "v")
    await c.close()
    assert c.closed.is_set()
    assert c._writer is None
    # double-close is safe, and no task is left pumping the dead socket
    await c.close()
    leftover = [t for t in asyncio.all_tasks()
                if t is not asyncio.current_task() and not t.done()
                and "sweeper" not in repr(t)]
    server.close()
    await server.wait_closed()
    assert not [t for t in leftover if "KvClient" in repr(t)]


async def test_session_close_leaves_no_stray_tasks():
    base = set(asyncio.all_tasks())  # harness wrapper tasks are not leaks
    server, store, port = await _start()
    sess = await StoreSession(port=port).connect()
    await sess.lease_grant(1.0)
    watch = await sess.watch_prefix("p/")
    sub = await sess.subscribe("topic.>")
    await sess.close()
    server.close()
    await server.wait_closed()
    await asyncio.sleep(0.05)
    leftover = [t for t in asyncio.all_tasks()
                if t not in base and t is not asyncio.current_task()
                and not t.done()]
    assert not leftover, f"stray tasks after close: {leftover}"
    # closed watches/subs terminate their consumers
    with pytest.raises(StopAsyncIteration):
        await watch.__anext__()
    with pytest.raises(StopAsyncIteration):
        await sub.__anext__()


async def test_publisher_rekey_rewrites_queued_payloads():
    """A KV event offered before a rekey must not be published on the
    NEW worker's topic still carrying the OLD worker_id — routers
    attribute blocks by the id inside the event, so that pairing would
    briefly credit the new worker's topic stream to the old worker."""
    import json

    from dynamo_tpu.kv_router.protocols import KvCacheEvent, KvEventKind
    from dynamo_tpu.runtime.publisher import KvEventPublisher

    class _Kv:
        def __init__(self):
            self.published = []

        async def publish(self, topic, value):
            self.published.append((topic, json.loads(value)))

    kv = _Kv()
    pub = KvEventPublisher(kv, "111")
    pub(KvCacheEvent(kind=KvEventKind.REMOVED, removed_hashes=[7]))
    # the rekey lands while the event is still queued (drain not started)
    pub.rekey("222", "kv_events.222")
    pub.start()
    for _ in range(100):
        if kv.published:
            break
        await asyncio.sleep(0.01)
    await pub.stop()
    assert [(t, p["worker_id"]) for t, p in kv.published] == [
        ("kv_events.222", "222")]
