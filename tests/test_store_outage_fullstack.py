"""Full-stack control-plane outage test (slow): the whole serving stack
— journal-backed store, resync-enabled workers registered via
register_llm, ModelWatcher frontend, HTTP chat — survives the store
being killed and WAL-restarted on the same port.

The contract under test (PR 15 tentpole, layer 3):
  * in-flight and new HTTP requests keep succeeding THROUGH the outage
    (streams flow worker<->frontend direct; degraded mode freezes the
    health/load views instead of evicting the fleet),
  * every session resyncs after the restart; leases are reclaimed from
    the replayed journal so the registry never churns,
  * greedy completions are token-identical before, during and after the
    bounce (differential pin).
"""
import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.frontend import HttpService, ModelManager
from dynamo_tpu.frontend.watcher import ModelEntry, ModelWatcher, register_llm
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import crash_store, serve_store

BS = 4


async def chat(client, content, max_tokens=6):
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "mock-model",
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens,
        },
    )
    return r


@pytest.mark.slow
async def test_serving_survives_store_bounce(tmp_path):
    jp = str(tmp_path / "store.wal")
    server, store = await serve_store(
        port=0, sweep_interval_s=0.05, journal_path=jp)
    port = server.sockets[0].getsockname()[1]

    workers = []
    for _ in range(2):
        rt = await DistributedRuntime.connect(port=port, resync=True)
        eng = MockerEngine(
            MockerArgs(speedup_ratio=100.0, page_size=BS, num_pages=64)
        )
        entry = ModelEntry(
            name="mock-model", namespace="outage", component="backend",
            block_size=BS, router_mode="kv",
        )
        served = await register_llm(rt, eng, entry, lease_ttl_s=1.0)
        workers.append((rt, eng, served))
    lease_ids = {served.lease_id for _, _, served in workers}

    frontend_rt = await DistributedRuntime.connect(port=port, resync=True)
    manager = ModelManager()
    watcher = await ModelWatcher(
        frontend_rt, manager, namespace="outage").start()
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    server2 = None
    try:
        for _ in range(200):
            push = watcher._routers.get("mock-model")
            if push is not None and len(push.workers) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(watcher._routers["mock-model"].workers) == 2

        prompt = "w1 w2 w3 w4 w5"
        r = await chat(client, prompt)
        assert r.status == 200
        ref = (await r.json())["choices"][0]["message"]["content"]

        crash_store(server)
        sessions = [rt.kv for rt, _, _ in workers] + [frontend_rt.kv]
        for _ in range(200):
            if all(s.degraded for s in sessions):
                break
            await asyncio.sleep(0.02)
        assert all(s.degraded for s in sessions)

        # DURING the outage: requests still route and stream (the
        # degraded frontend serves from its last-known fleet view), and
        # greedy output is identical
        for _ in range(3):
            r = await chat(client, prompt)
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["message"]["content"] == ref

        # outage outlives the lease TTL: only the replay grace window
        # (plus frozen frontend health) keeps the fleet registered
        await asyncio.sleep(1.2)
        server2, store2 = await serve_store(
            port=port, sweep_interval_s=0.05, journal_path=jp)
        assert store2.replayed_keys >= 2  # both registrations replayed

        for _ in range(400):
            if all(not s.degraded and s.resyncs >= 1 for s in sessions):
                break
            await asyncio.sleep(0.02)
        assert all(not s.degraded and s.resyncs >= 1 for s in sessions)

        # leases were RECLAIMED, not re-granted: same ids, no churn
        assert {served.lease_id for _, _, served in workers} == lease_ids
        regs = await frontend_rt.kv.get_prefix(
            "dynamo://outage/_components/backend/")
        assert {k.rsplit("/", 1)[1] for k, _, _ in regs} == {
            str(i) for i in lease_ids}

        # AFTER recovery: keepalives flow again — outlive a full TTL,
        # the fleet stays registered, output still token-identical
        await asyncio.sleep(1.2)
        assert len(watcher._routers["mock-model"].workers) == 2
        for _ in range(3):
            r = await chat(client, prompt)
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["message"]["content"] == ref
    finally:
        await client.close()
        await watcher.stop()
        await frontend_rt.close()
        for rt, eng, served in workers:
            await served.shutdown()
            await eng.stop()
            await rt.close()
        if server2 is not None:
            server2.close()
            store2.close_journal()
