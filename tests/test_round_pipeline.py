"""Round-pipelining correctness pins (EngineConfig.round_pipeline).

The pipeline dispatches round N+1's fused program before blocking on
round N's packed fetch — pure reordering of host work relative to
device work. Under greedy decoding the token streams must therefore be
BYTE-IDENTICAL with the pipeline on vs off, through every flush point:
admission bursts, mid-stream prefix-hit patches, speculative rounds,
priority preemption, graceful drain, and a chaos kill with a round in
flight (migration replay).

The off mode (``round_pipeline=False``) is the legacy serialized round
order — the differential baseline, kept reachable exactly for these
tests and for ``--round-pipeline off`` triage in production.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.overload.errors import PreemptedError
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.resilience import CHAOS, RESILIENCE

PS = 16


@pytest.fixture(autouse=True)
def _reset_globals():
    RESILIENCE.reset()
    CHAOS.reset()
    yield
    RESILIENCE.reset()
    CHAOS.reset()


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    return cfg, llama.init_params(cfg, 0)


def _mk(setup, **kw) -> TpuEngine:
    cfg, params = setup
    base = dict(
        num_pages=128, page_size=PS, max_pages_per_seq=16,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32",
    )
    base.update(kw)
    return TpuEngine(cfg, EngineConfig(**base), params=params,
                     mesh_config=MeshConfig(tp=1))


def _req(prompt, max_tokens, priority=0):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        priority=priority,
    )


async def _collect(eng, req):
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


async def _run_jobs(eng, jobs):
    """jobs: list of (prompt, max_tokens, delay_s). Staggered submission
    creates admission bursts against live decode; varied max_tokens
    creates mid-window release patches."""
    async def one(p, mt, delay):
        if delay:
            await asyncio.sleep(delay)
        return await _collect(eng, _req(p, mt))

    return await asyncio.gather(
        *[one(p, mt, d) for (p, mt, d) in jobs]
    )


async def _both_modes(setup, jobs, **kw):
    """Run the same job list pipelined and serialized; return
    (tokens_on, tokens_off, pipeline_stats_on)."""
    out = {}
    for mode in (True, False):
        eng = _mk(setup, round_pipeline=mode, **kw)
        eng.start()
        try:
            toks = await _run_jobs(eng, jobs)
            stats = eng.pipeline_stats()
        finally:
            await eng.stop()
        out[mode] = (toks, stats)
    assert out[False][1]["pipelined_dispatches"] == 0
    return out[True][0], out[False][0], out[True][1]


async def test_differential_admission_burst_and_releases(setup):
    """Admission bursts mid-decode + staggered releases: every arrival
    forces a pipeline flush (patches must not race an in-flight round)
    and every early finisher exercises the release flush point."""
    rng = np.random.RandomState(0)
    jobs = [
        (rng.randint(1, 256, 48).tolist(), 40, 0.0),
        (rng.randint(1, 256, 24).tolist(), 12, 0.0),   # early release
        (rng.randint(1, 256, 40).tolist(), 32, 0.15),  # burst arrival
        (rng.randint(1, 256, 17).tolist(), 20, 0.3),   # second burst
    ]
    on, off, stats = await _both_modes(setup, jobs)
    assert on == off, "pipelined tokens diverged from serialized run"
    assert stats["pipelined_dispatches"] > 0, stats
    assert stats["pipe_flushes"]["admission"] > 0, stats


async def test_differential_mid_stream_prefix_hit_patch(setup):
    """A prefix-cache-hit admission lands mid-decode: the load_ctx +
    patch pair against pool state must flush the in-flight round first.
    The shared-prefix follower must emit exactly what the serialized
    engine emits."""
    rng = np.random.RandomState(1)
    head = rng.randint(1, 256, 3 * PS).tolist()  # seals 3 blocks
    jobs = [
        (head + [7], 36, 0.0),
        (rng.randint(1, 256, 32).tolist(), 36, 0.0),
        (head + [9], 24, 0.4),   # arrives mid-decode, hits the prefix
    ]
    on, off, stats = await _both_modes(setup, jobs)
    assert on == off
    assert stats["pipelined_dispatches"] > 0, stats


async def test_differential_spec_rounds(setup):
    """Speculative rounds never overlap a normal in-flight round (the
    verify/rollback patches touch the same slot state): greedy n-gram
    output stays identical with the pipeline on."""
    rng = np.random.RandomState(2)
    pat = rng.randint(1, 256, 8).tolist()
    jobs = [
        (pat * 4, 24, 0.0),                           # spec-friendly
        (rng.randint(1, 256, 20).tolist(), 24, 0.0),  # reject-heavy
    ]
    on, off, stats = await _both_modes(
        setup, jobs, speculative="ngram", num_speculative_tokens=4,
        max_decode_slots=2, num_pages=64, max_pages_per_seq=8,
        prefill_buckets=(32, 64),
    )
    assert on == off, "speculative pipelined run diverged"
    assert stats["pipe_flushes"]["spec"] > 0, stats


async def test_differential_preemption(setup):
    """Priority preemption with a round in flight: in both modes the
    victim fails with the retriable PreemptedError after emitting a
    clean prefix of the unloaded run, and the high-priority request's
    tokens are identical across modes."""
    rng = np.random.RandomState(3)
    victim_p = rng.randint(1, 256, 40).tolist()
    high_p = rng.randint(1, 256, 24).tolist()

    ref_eng = _mk(setup, round_pipeline=True)
    ref_eng.start()
    expected = await _collect(ref_eng, _req(victim_p, 100))
    await ref_eng.stop()

    high_toks = {}
    for mode in (True, False):
        eng = _mk(setup, round_pipeline=mode, max_decode_slots=1,
                  preempt_running=True)
        eng.start()
        got: list[int] = []

        async def run_victim(eng=eng, got=got):
            async for out in eng.generate(_req(victim_p, 100)):
                got.extend(out.token_ids)

        vt = asyncio.ensure_future(run_victim())
        for _ in range(2000):
            if len(got) >= 8:
                break
            await asyncio.sleep(0.005)
        assert len(got) >= 8, "victim never started streaming"
        high_toks[mode] = await _collect(eng, _req(high_p, 6, priority=1))
        with pytest.raises(PreemptedError):
            await vt
        assert eng.preempt_migrations == 1
        # the victim's partial stream is a clean prefix — no torn round
        assert got == expected[:len(got)], mode
        await eng.stop()
    assert high_toks[True] == high_toks[False]


async def test_differential_drain(setup):
    """begin_drain with requests in flight: both modes run the in-flight
    work to completion (identical tokens), refuse new admissions, and
    report drained."""
    from dynamo_tpu.resilience import WorkerDrainingError

    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 256, 32).tolist() for _ in range(3)]
    out = {}
    for mode in (True, False):
        eng = _mk(setup, round_pipeline=mode)
        eng.start()
        tasks = [asyncio.ensure_future(_collect(eng, _req(p, 32)))
                 for p in prompts]
        await asyncio.sleep(0.2)   # let decode get going
        eng.begin_drain()
        with pytest.raises(WorkerDrainingError):
            await _collect(eng, _req(prompts[0], 4))
        out[mode] = await asyncio.gather(*tasks)
        for _ in range(2000):
            if eng.drained():
                break
            await asyncio.sleep(0.005)
        assert eng.drained(), mode
        await eng.stop()
    assert out[True] == out[False]
    assert all(len(t) == 32 for t in out[True])


async def test_chaos_kill_with_round_in_flight_replays_identically(setup):
    """The keystone: a chaos worker-kill fired while the pipelined
    engine has a round in flight must leave the migrated client with
    the BYTE-IDENTICAL stream of an uninterrupted run — the replay
    prefill over prompt+emitted picks up exactly where the dead stream
    stopped, torn in-flight round discarded."""
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 256, 40).tolist()

    ref_eng = _mk(setup, round_pipeline=True)
    ref_eng.start()
    expected = await _collect(ref_eng, _req(prompt, 24))
    await ref_eng.stop()

    eng = _mk(setup, round_pipeline=True)
    eng.start()

    class ChaosWorker:
        """The remote_engine integration shape: the engine stream runs
        through the chaos plane when any point is armed."""

        def __init__(self, inner):
            self.inner = inner

        async def generate(self, req):
            src = self.inner.generate(req)
            if CHAOS.any_armed():
                src = CHAOS.wrap_stream(src)
            async for out in src:
                yield out

    # the same live engine behind two worker ids: the replay lands on a
    # warm engine whose pipeline is already running
    router = KvRouter(PS, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router, {"w0": ChaosWorker(eng),
                                 "w1": ChaosWorker(eng)})
    CHAOS.arm("kill_worker", after_outputs=6, once=True)
    got = []
    async for out in push.generate(_req(prompt, 24)):
        got.extend(out.token_ids)
    stats = eng.pipeline_stats()
    await eng.stop()

    assert got == expected, "migrated stream diverged from clean run"
    assert CHAOS.points["kill_worker"].injected_total == 1
    assert push.migrations == 1
    assert RESILIENCE.get("dynamo_migration_total") == 1
    # the kill really did land with the pipeline active
    assert stats["pipelined_dispatches"] > 0, stats
