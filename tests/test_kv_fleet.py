"""Fleet-wide KV prefix economy (kv_router/fleet.py, kv_router/
prefetch.py, the dedup-admission path in engine.py, and the
replication-aware eviction in engine/offload.py).

Keystones: (1) the indexer's access heat is EWMA-decayed and bounded —
no unbounded ``_freq`` growth, re-store after TTL expiry starts cold;
(2) churn (worker removal, TTL sweeps, duplicate/late REMOVEDs) never
drives replica counts negative or corrupts the holder view; (3) the
dedup-by-hash admission arm is token-identical to the recompute arm —
hints change WHERE bytes come from, never what tokens come out; (4) a
prefetched page rotted in place (``corrupt_prefetch`` chaos) is caught
by the PR-8 onboard verify and quarantined without output divergence.
"""
import asyncio
import json
import sys
from pathlib import Path

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.offload import HostOffloadTier
from dynamo_tpu.kv_fleet_metrics import KV_FLEET
from dynamo_tpu.kv_integrity import KV_INTEGRITY
from dynamo_tpu.kv_router.fleet import FleetHints, FleetKvView
from dynamo_tpu.kv_router.indexer import _PRUNE_EVERY, KvIndexer
from dynamo_tpu.kv_router.prefetch import (
    KvPrefetchController,
    PrefetchConfig,
)
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent,
    KvEventKind,
    StoredBlock,
)
from dynamo_tpu.kv_transfer import (
    BlocksetDescriptor,
    BlockTransferServer,
    KvCacheLayout,
    RemoteKvFetcher,
    publish_descriptor,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.resilience.chaos import CHAOS
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store
from dynamo_tpu.tokens import compute_block_hashes

REPO_ROOT = Path(__file__).resolve().parents[1]
BS = 4   # router-side block size
PS = 16  # engine-side page size
SHAPE = (2, 2, 1, PS, 4)  # (2, L, kvh, ps, hd)


@pytest.fixture(autouse=True)
def _clean_chaos():
    CHAOS.reset()
    yield
    CHAOS.reset()


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def stored(worker, hashes, parent=0):
    return KvCacheEvent(
        kind=KvEventKind.STORED,
        worker_id=worker,
        parent_hash=parent,
        blocks=[StoredBlock(block_hash=h) for h in hashes],
    )


def removed(worker, hashes):
    return KvCacheEvent(
        kind=KvEventKind.REMOVED, worker_id=worker, removed_hashes=hashes
    )


def _pages(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(
        SHAPE[:3] + (n,) + SHAPE[3:]
    ).astype(np.float32)


# ---------------------------------------------------------------------------
# indexer heat: EWMA decay, bounded growth, TTL interaction


def test_heat_decays_with_halflife():
    clk = FakeClock()
    idx = KvIndexer(BS, freq_halflife_s=10.0, clock=clk)
    hashes = compute_block_hashes(list(range(1, 5)), BS)  # 1 block
    idx.apply_event(stored("w0", hashes))
    for _ in range(4):
        idx.find_matches(hashes)
    assert idx.heat(hashes[0]) == pytest.approx(4.0)
    clk.advance(10.0)
    assert idx.heat(hashes[0]) == pytest.approx(2.0)
    clk.advance(20.0)
    assert idx.heat(hashes[0]) == pytest.approx(0.5)
    # a fresh touch decays first, then adds 1
    idx.find_matches(hashes)
    assert idx.heat(hashes[0]) == pytest.approx(1.5)


def test_no_decay_when_halflife_unset_preserves_raw_counters():
    clk = FakeClock()
    idx = KvIndexer(BS, clock=clk)  # legacy: raw counters
    hashes = compute_block_hashes(list(range(1, 5)), BS)
    idx.apply_event(stored("w0", hashes))
    s1 = idx.find_matches(hashes)
    assert s1.frequencies == []  # pre-touch freq 0 omitted
    clk.advance(1e6)             # irrelevant without a half-life
    s2 = idx.find_matches(hashes)
    assert s2.frequencies == [1]
    s3 = idx.find_matches(hashes)
    assert s3.frequencies == [2]


def test_freq_table_is_pruned_and_bounded():
    clk = FakeClock()
    idx = KvIndexer(BS, freq_halflife_s=1.0, clock=clk)
    # 32 distinct single-block prefixes, each touched once
    for i in range(32):
        hs = compute_block_hashes([1000 + i] * BS, BS)
        idx.apply_event(stored("w0", hs))
        idx.find_matches(hs)
    assert len(idx._freq) == 32
    clk.advance(1000.0)  # everything decays to ~0
    # the opportunistic prune runs every _PRUNE_EVERY applied events
    filler = compute_block_hashes([7] * BS, BS)
    for _ in range(_PRUNE_EVERY):
        idx.apply_event(stored("w0", filler))
    assert len(idx._freq) == 0
    assert idx.hot_blocks(10) == []


def test_restore_after_ttl_expiry_resets_heat():
    clk = FakeClock()
    idx = KvIndexer(BS, expiration_s=5.0, freq_halflife_s=1e9, clock=clk)
    hashes = compute_block_hashes(list(range(1, 5)), BS)
    idx.apply_event(stored("w0", hashes))
    for _ in range(8):
        idx.find_matches(hashes)
    assert idx.heat(hashes[0]) >= 8.0
    # the copy's TTL lapses, then a NEW store lands before any query
    # swept the stale entry: the previous life's heat must not carry over
    clk.advance(6.0)
    idx.apply_event(stored("w0", hashes))
    assert idx.heat(hashes[0]) == 0.0
    assert idx.replicas(hashes[0]) == 1


def test_restore_within_ttl_keeps_heat():
    clk = FakeClock()
    idx = KvIndexer(BS, expiration_s=60.0, freq_halflife_s=1e9, clock=clk)
    hashes = compute_block_hashes(list(range(1, 5)), BS)
    idx.apply_event(stored("w0", hashes))
    idx.find_matches(hashes)
    idx.find_matches(hashes)
    clk.advance(10.0)  # well inside the TTL
    idx.apply_event(stored("w1", hashes))  # a second replica, same life
    assert idx.heat(hashes[0]) == pytest.approx(2.0)
    assert idx.replicas(hashes[0]) == 2


# ---------------------------------------------------------------------------
# churn: replica view stays consistent


def test_duplicate_and_late_removes_never_go_negative():
    idx = KvIndexer(BS)
    hashes = compute_block_hashes(list(range(1, 9)), BS)  # 2 blocks
    idx.apply_event(stored("w0", hashes))
    idx.apply_event(removed("w0", [hashes[0]]))
    idx.apply_event(removed("w0", [hashes[0]]))  # duplicate
    idx.apply_event(removed("w1", [hashes[1]]))  # from a non-holder
    idx.apply_event(removed("w2", [424242]))     # never stored
    assert idx.replicas(hashes[0]) == 0
    assert idx.replicas(hashes[1]) == 1
    assert idx.holders(hashes[1]) == {"w0"}
    # re-store after full removal works from scratch
    idx.apply_event(stored("w3", hashes))
    assert idx.replicas(hashes[0]) == 1
    assert idx.holders(hashes[0]) == {"w3"}


def test_worker_removal_interleaved_with_ttl_sweep():
    clk = FakeClock()
    idx = KvIndexer(BS, expiration_s=5.0, freq_halflife_s=1e9, clock=clk)
    hashes = compute_block_hashes(list(range(1, 9)), BS)
    idx.apply_event(stored("w0", hashes))
    idx.apply_event(stored("w1", hashes))
    assert idx.replicas(hashes[0]) == 2
    clk.advance(6.0)
    # the TTL sweep fires from the query path and drops BOTH holders
    assert idx.find_matches(hashes).scores == {}
    assert idx.replicas(hashes[0]) == 0
    # a late REMOVED from an already-swept holder is a no-op
    idx.apply_event(removed("w0", list(hashes)))
    idx.remove_worker("w1")
    assert idx.replicas(hashes[0]) == 0
    assert idx.total_blocks() == 0
    # the hash can live again, heat reset
    idx.apply_event(stored("w0", hashes))
    assert idx.find_matches(hashes).scores == {"w0": 2}
    assert idx.heat(hashes[0]) == pytest.approx(1.0)  # the one new touch


def test_remove_worker_drops_hot_set_membership():
    idx = KvIndexer(BS, freq_halflife_s=600.0)
    hashes = compute_block_hashes(list(range(1, 9)), BS)
    idx.apply_event(stored("w0", hashes))
    idx.find_matches(hashes)
    idx.find_matches(hashes)
    assert [h for h, _ in idx.hot_blocks(10)] != []
    idx.remove_worker("w0")
    # hot_blocks only reports currently-HELD hashes
    assert idx.hot_blocks(10) == []
    assert idx.worker_block_count("w0") == 0


# ---------------------------------------------------------------------------
# FleetKvView: chains, hot set, digests


def _warm_view(touches=2):
    idx = KvIndexer(BS, freq_halflife_s=600.0)
    hashes = compute_block_hashes(list(range(1, 17)), BS)  # 4 blocks
    idx.apply_event(stored("warm", hashes))
    for _ in range(touches):
        idx.find_matches(hashes)
    return FleetKvView(idx), hashes


def test_chain_of_reconstructs_root_first_run():
    view, hashes = _warm_view()
    assert view.chain_of(hashes[3]) == hashes
    assert view.chain_of(hashes[1]) == hashes[:2]
    # a chain stops where the parent is no longer held anywhere
    view.indexer.apply_event(removed("warm", [hashes[0]]))
    assert view.chain_of(hashes[3]) == hashes[1:]


def test_hot_chains_cover_the_hot_set_without_redundant_prefixes():
    view, hashes = _warm_view()
    chains = view.hot_chains(4)
    assert chains, "touched blocks must surface as hot chains"
    covered = {h for c in chains for h in c}
    assert covered == set(hashes)
    for c in chains:
        assert c[0] == hashes[0]  # root-first
        assert c == hashes[: len(c)]
    # the full run appears exactly once (prefixes of a selected chain
    # are never re-emitted as their own chain)
    assert sum(1 for c in chains if c == hashes) == 1


def test_under_replicated_reports_hot_singletons_only():
    view, hashes = _warm_view()
    under = view.under_replicated(target=2, k=10)
    assert {h for h, r, _ in under} == set(hashes)
    assert all(r == 1 for _, r, _ in under)
    # a second replica of the leaf takes it off the list
    view.indexer.apply_event(stored("other", [hashes[3]]))
    under = view.under_replicated(target=2, k=10)
    assert hashes[3] not in {h for h, _, _ in under}


def test_digest_roundtrips_through_json_into_fleet_hints():
    view, hashes = _warm_view()
    digest = json.loads(json.dumps(view.digest()))  # wire trip
    hints = FleetHints(digest)
    assert hints.applied == 1
    for h in hashes:
        assert hints.replicas(h) == 1
        assert hints.holders(h) == ["warm"]
    assert hints.replicas(999_999) is None  # unknown, not 0
    assert set(hints.hot) == set(hashes)
    d = hints.to_dict()
    assert d["applied"] == 1 and d["known_blocks"] == len(hashes)


def test_view_to_dict_shape_for_debug_endpoint():
    view, hashes = _warm_view()
    body = view.to_dict(top=2)
    assert body["total_blocks"] == 4
    assert len(body["hot"]) == 2
    for row in body["hot"]:
        assert set(row) == {"hash", "heat", "replicas", "holders",
                            "chain_len"}
        assert row["replicas"] == 1 and row["holders"] == ["warm"]


# ---------------------------------------------------------------------------
# replication-aware eviction (G2/G3 _PageTier)


def test_eviction_without_hints_is_plain_lru():
    t = HostOffloadTier(3, SHAPE, np.float32)
    batch = _pages(4)
    assert t.put_batch([1, 2, 3], [0, 1, 2], batch[:, :, :, :3]) == 3
    t.put_one(4, 3, batch[:, :, :, 3])
    assert 1 not in t and 2 in t and 4 in t  # LRU head evicted


def test_eviction_prefers_replicated_blocks_over_last_copy():
    t = HostOffloadTier(3, SHAPE, np.float32)
    batch = _pages(4)
    t.put_batch([1, 2, 3], [0, 1, 2], batch[:, :, :, :3])
    # fleet says: block 2 has 3 copies elsewhere; 1 and 3 are last copies
    t.fleet_replicas = {1: 1, 2: 3, 3: 1}.get
    before = KV_FLEET.get("dynamo_kv_fleet_replicated_evictions_total")
    t.put_one(4, 3, batch[:, :, :, 3])
    assert 2 not in t           # the well-replicated block went first
    assert 1 in t and 3 in t    # both last copies survive
    assert KV_FLEET.get(
        "dynamo_kv_fleet_replicated_evictions_total"
    ) == before + 1


def test_eviction_falls_back_to_head_and_counts_last_copy():
    t = HostOffloadTier(2, SHAPE, np.float32)
    batch = _pages(3)
    t.put_batch([1, 2], [0, 1], batch[:, :, :, :2])
    t.fleet_replicas = lambda h: 1  # every block is the fleet's last copy
    before = KV_FLEET.get("dynamo_kv_fleet_last_copy_evictions_total")
    t.put_one(3, 2, batch[:, :, :, 2])
    assert 1 not in t and 2 in t  # LRU order still decides
    assert KV_FLEET.get(
        "dynamo_kv_fleet_last_copy_evictions_total"
    ) == before + 1
    # unknown replica counts do NOT inflate the last-copy counter
    t.fleet_replicas = lambda h: None
    mid = KV_FLEET.get("dynamo_kv_fleet_last_copy_evictions_total")
    t.put_one(4, 3, batch[:, :, :, 0])
    assert KV_FLEET.get(
        "dynamo_kv_fleet_last_copy_evictions_total"
    ) == mid


def test_rot_page_breaks_verification_without_touching_crc():
    t = HostOffloadTier(4, SHAPE, np.float32)
    batch = _pages(2)
    t.put_batch([1, 2], [0, 1], batch)
    assert t.verify_pages([1, 2], t.gather([1, 2])) == []
    assert t.rot_page(1) is True
    assert t.verify_pages([1, 2], t.gather([1, 2])) == [0]
    assert t.verify_pages([2], t.gather([2])) == []  # 2 untouched
    assert t.rot_page(999) is False  # absent hash: no-op


# ---------------------------------------------------------------------------
# replication controller


class StubWorker:
    def __init__(self):
        self.hints = []
        self.prefetched = []

    def apply_fleet_hints(self, digest):
        self.hints.append(digest)

    async def prefetch_hashes(self, hashes, parents=None):
        self.prefetched.append((list(hashes), list(parents or [])))
        return len(hashes)


async def test_controller_warm_starts_cold_worker_and_replicates():
    clk = FakeClock()
    view, hashes = _warm_view()
    workers = {"warm": StubWorker(), "cold": StubWorker()}
    ctrl = KvPrefetchController(
        view, lambda: workers,
        PrefetchConfig(replication_target=2, hot_k=4, cooldown_s=30.0),
        clock=clk,
    )
    warm_before = KV_FLEET.get("dynamo_kv_fleet_warm_starts_total")
    pushed = await ctrl.tick()
    # every worker got the hint digest
    assert len(workers["warm"].hints) == 1
    assert len(workers["cold"].hints) == 1
    assert workers["cold"].hints[0]["replicas"]
    # the cold worker (zero fleet footprint) was warm-started with the
    # full hot run, root-first, parents aligned
    assert pushed > 0
    assert KV_FLEET.get(
        "dynamo_kv_fleet_warm_starts_total"
    ) == warm_before + 1
    got_hashes, got_parents = workers["cold"].prefetched[0]
    assert got_hashes == hashes[: len(got_hashes)]
    assert got_parents[1:] == got_hashes[:-1]
    # the warm worker already holds everything: nothing pushed to it
    assert workers["warm"].prefetched == []

    # same tick again inside the cooldown window: hints flow, no re-push
    n2 = await ctrl.tick()
    assert n2 == 0
    assert len(workers["cold"].hints) == 2

    # after the cooldown, the still-under-replicated chain goes to the
    # least-loaded non-holder (the indexer never saw cold store it)
    clk.advance(31.0)
    n3 = await ctrl.tick()
    assert n3 > 0
    assert len(workers["cold"].prefetched) >= 2


async def test_controller_publishes_to_hookless_workers():
    view, hashes = _warm_view()
    sent = []

    async def publish(wid, msg):
        sent.append((wid, msg))

    # a worker object with no duck-typed hooks: wire delivery only
    workers = {"remote": object()}
    ctrl = KvPrefetchController(
        view, lambda: workers,
        PrefetchConfig(replication_target=2, hot_k=4),
        publish=publish,
    )
    await ctrl.tick()
    kinds = {next(iter(m)) for _, m in sent}
    assert kinds == {"hints", "prefetch"}
    pf = [m["prefetch"] for _, m in sent if "prefetch" in m][0]
    assert pf["hashes"] == hashes[: len(pf["hashes"])]
    assert len(pf["parents"]) == len(pf["hashes"])


async def test_controller_skips_empty_fleet_and_undeliverable_workers():
    view, _ = _warm_view()
    ctrl = KvPrefetchController(view, lambda: {})
    assert await ctrl.tick() == 0
    # deliverable nowhere (no hooks, no publish): no pushes, no crash
    ctrl2 = KvPrefetchController(view, lambda: {"w": object()})
    assert await ctrl2.tick() == 0


# ---------------------------------------------------------------------------
# engine integration: dedup admission + prefetch + chaos


def _ecfg(**kw):
    base = dict(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", flush_every=2, max_inflight_rounds=1,
    )
    base.update(kw)
    return EngineConfig(**base)


async def _collect(eng, prompt, n=6):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n, ignore_eos=True),
    )
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


async def _warm_fleet(kv, topic):
    """One warm engine serving its sealed pool on the transfer plane;
    returns (warm, server, prompt, warm_toks, hashes)."""
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    warm = TpuEngine(cfg, _ecfg(), params=params,
                     mesh_config=MeshConfig(tp=1))
    prompt = list(range(1, PS * 3 + 4))
    warm_toks = await _collect(warm, prompt)
    srv = BlockTransferServer(
        read_fn=warm.export_pages,
        read_hashes_fn=warm.export_pages_by_hash,
    )
    host, sport = await srv.start()
    await publish_descriptor(kv, topic, BlocksetDescriptor(
        worker_id="warm", host=host, port=sport,
        layout=KvCacheLayout(
            num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
            page_size=PS, head_dim=cfg.head_dim, dtype="float32",
        ),
    ))
    hashes = compute_block_hashes(prompt, PS)[:3]
    return warm, srv, cfg, params, prompt, warm_toks, hashes


def _holder_digest(hashes, holder="warm"):
    return {
        "replicas": {str(h): 1 for h in hashes},
        "holders": {str(h): [holder] for h in hashes},
        "hot": list(hashes),
    }


@pytest.mark.asyncio_timeout(300)
async def test_dedup_admission_arms_are_token_identical():
    """Three cold arms against one warm peer: (a) fleet-hinted holder —
    pull, count recompute-avoided; (b) dedup on but the digest knows
    nothing of these blocks — probe round skipped, local recompute; (c)
    dedup off — legacy probe behavior. All token-identical."""
    server, _store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kvs = [await KvClient(port=port).connect() for _ in range(4)]
    warm, srv, cfg, params, prompt, warm_toks, hashes = (
        await _warm_fleet(kvs[0], "g4f")
    )
    mk = lambda **kw: TpuEngine(  # noqa: E731
        cfg, _ecfg(host_offload_pages=16, **kw), params=params,
        mesh_config=MeshConfig(tp=1),
    )
    hinted, unknown, off = mk(), mk(), mk(kv_dedup_admission=False)
    try:
        # (a) the digest names the warm holder: fetch lands, the avoided
        # recompute is counted
        hinted.remote_kv = RemoteKvFetcher(kvs[1], "g4f", "hinted")
        hinted.apply_fleet_hints(_holder_digest(hashes))
        avoided0 = KV_FLEET.get(
            "dynamo_kv_fleet_recompute_avoided_blocks_total"
        )
        assert await _collect(hinted, prompt) == warm_toks
        assert hinted.remote_kv.hits == 1
        assert hinted.remote_onboard_blocks == 3
        assert KV_FLEET.get(
            "dynamo_kv_fleet_recompute_avoided_blocks_total"
        ) == avoided0 + 3

        # (b) dedup on, digest entirely ignorant of this prefix: the
        # probe round is skipped, the prefix recomputes locally — same
        # tokens, zero wire traffic
        unknown.remote_kv = RemoteKvFetcher(kvs[2], "g4f", "unknown")
        unknown.apply_fleet_hints(_holder_digest([123456789]))
        skip0 = KV_FLEET.get("dynamo_kv_fleet_dedup_skipped_probes_total")
        assert await _collect(unknown, prompt) == warm_toks
        assert unknown.remote_kv.fetches == 0
        assert KV_FLEET.get(
            "dynamo_kv_fleet_dedup_skipped_probes_total"
        ) == skip0 + 1

        # (c) dedup admission off: same ignorant digest applied, but the
        # gate ignores it — the legacy probe runs and still finds warm
        off.remote_kv = RemoteKvFetcher(kvs[3], "g4f", "off")
        off.apply_fleet_hints(_holder_digest([123456789]))
        assert await _collect(off, prompt) == warm_toks
        assert off.remote_kv.fetches >= 1
        assert off.remote_kv.hits == 1
        await srv.stop()
    finally:
        for e in (warm, hinted, unknown, off):
            await e.stop()
        for kv in kvs:
            await kv.close()
        server.close()


@pytest.mark.asyncio_timeout(240)
async def test_prefetch_hashes_lands_ahead_of_demand():
    """A controller-style prefetch push fills the cold worker's G2 tier
    BEFORE the request arrives: the demand path then never touches the
    wire, and the stream matches the warm worker token-for-token."""
    server, _store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kv_a = await KvClient(port=port).connect()
    kv_b = await KvClient(port=port).connect()
    warm, srv, cfg, params, prompt, warm_toks, hashes = (
        await _warm_fleet(kv_a, "g4p")
    )
    cold = TpuEngine(cfg, _ecfg(host_offload_pages=16), params=params,
                     mesh_config=MeshConfig(tp=1))
    try:
        cold.remote_kv = RemoteKvFetcher(kv_b, "g4p", "cold")
        pf0 = KV_FLEET.get("dynamo_kv_fleet_prefetched_blocks_total")
        n = await cold.prefetch_hashes(list(hashes))
        assert n == 3
        assert KV_FLEET.get(
            "dynamo_kv_fleet_prefetched_blocks_total"
        ) == pf0 + 3
        # land the queued pages in G2 (the engine loop does this on its
        # own cadence; the direct drain makes the test deterministic)
        cold._drain_host_ingest()
        assert all(h in cold.offload for h in hashes)
        # a repeat push is a full local hit: no second fetch
        assert await cold.prefetch_hashes(list(hashes)) == 0

        fetches = cold.remote_kv.fetches
        assert await _collect(cold, prompt) == warm_toks
        assert cold.remote_kv.fetches == fetches  # demand stayed local
        assert cold.offload.onboard_hits >= 3
        await srv.stop()
    finally:
        await warm.stop()
        await cold.stop()
        await kv_a.close()
        await kv_b.close()
        server.close()


@pytest.mark.asyncio_timeout(240)
async def test_corrupt_prefetch_chaos_quarantines_without_divergence():
    """Silent rot on a fleet-prefetched page (post-seal, crc untouched)
    must be caught by the onboard verify: the block is quarantined and
    recomputed, and the stream stays token-identical to the warm run."""
    server, _store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kv_a = await KvClient(port=port).connect()
    kv_b = await KvClient(port=port).connect()
    warm, srv, cfg, params, prompt, warm_toks, hashes = (
        await _warm_fleet(kv_a, "g4c")
    )
    cold = TpuEngine(cfg, _ecfg(host_offload_pages=16), params=params,
                     mesh_config=MeshConfig(tp=1))
    try:
        cold.remote_kv = RemoteKvFetcher(kv_b, "g4c", "cold")
        CHAOS.arm("corrupt_prefetch", probability=1.0, once=True)
        rec0 = KV_INTEGRITY.get("dynamo_kv_integrity_recomputed_total")
        quar0 = KV_INTEGRITY.get("dynamo_kv_integrity_quarantined_total")
        assert await _collect(cold, prompt) == warm_toks
        assert cold.remote_kv.hits == 1  # the fetch itself succeeded
        assert KV_INTEGRITY.get(
            "dynamo_kv_integrity_quarantined_total"
        ) > quar0
        assert KV_INTEGRITY.get(
            "dynamo_kv_integrity_recomputed_total"
        ) > rec0
        await srv.stop()
    finally:
        await warm.stop()
        await cold.stop()
        await kv_a.close()
        await kv_b.close()
        server.close()


# ---------------------------------------------------------------------------
# tools/kv_fleet.py exit contract (like tools/dynlint.py's)


async def _run_tool(*args):
    proc = await asyncio.create_subprocess_exec(
        sys.executable, str(REPO_ROOT / "tools" / "kv_fleet.py"), *args,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE,
        cwd=str(REPO_ROOT),
    )
    out, err = await proc.communicate()
    return proc.returncode, out.decode(), err.decode()


async def test_kv_fleet_tool_exit_contract():
    from aiohttp.test_utils import TestServer

    from dynamo_tpu.frontend import HttpService, ModelManager

    view, hashes = _warm_view()
    svc = HttpService(ModelManager())
    svc.fleet_views = {"tiny": view}
    server = TestServer(svc.app)
    await server.start_server()
    addr = f"127.0.0.1:{server.port}"
    try:
        # 0: populated view, JSON on stdout
        rc, out, _ = await _run_tool("--frontend", addr, "--top", "2")
        assert rc == 0, out
        body = json.loads(out)
        assert body["models"]["tiny"]["total_blocks"] == 4
        assert len(body["models"]["tiny"]["hot"]) == 2

        rc, out, _ = await _run_tool(
            "--frontend", addr, "--model", "tiny"
        )
        assert rc == 0 and json.loads(out)["models"]["tiny"]

        # 1: reachable but empty (no kv-routed model has blocks)
        svc.fleet_views["tiny"] = FleetKvView(KvIndexer(BS))
        rc, out, _ = await _run_tool("--frontend", addr)
        assert rc == 1
        assert json.loads(out)["models"]["tiny"]["total_blocks"] == 0

        # 2: unknown model (frontend 404s), unreachable frontend, usage
        rc, _, err = await _run_tool("--frontend", addr, "--model", "no")
        assert rc == 2 and "HTTP 404" in err
        rc, _, err = await _run_tool("--frontend", "127.0.0.1:1")
        assert rc == 2 and "cannot reach" in err
        rc, _, _ = await _run_tool("--frontend", addr, "--top", "0")
        assert rc == 2
        rc, _, _ = await _run_tool()  # missing --frontend
        assert rc == 2
    finally:
        await server.close()
