"""Telemetry subsystem tests: histogram bucket accounting, trace-context
round-trip through the runtime protocol, flight-recorder ring wraparound,
and the frontend e2e span tree + populated /metrics histograms
(ISSUE 3 acceptance criteria).
"""
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.backend import Backend
from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
from dynamo_tpu.preprocessor import OpenAIPreprocessor
from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.telemetry import (
    TRACES,
    FlightRecorder,
    Histogram,
    TelemetryRegistry,
    TraceStore,
    request_histograms,
)
from dynamo_tpu.telemetry.metrics import (
    percentile_from_snapshot,
    weighted_percentile,
)
from dynamo_tpu.telemetry.trace import Span, span_now
from dynamo_tpu.tokenizer import make_test_tokenizer

WORDS = [f"w{i}" for i in range(50)] + ["hello", "world"]


# ---------------------------------------------------------------------------
# histograms

def test_histogram_bucket_accounting():
    h = Histogram("t_seconds", "test", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    # cumulative counts per le edge, +Inf last
    assert snap["buckets"] == [0.1, 1.0, 10.0]
    assert snap["counts"] == [1, 3, 4, 5]
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(0.05 + 0.5 + 0.5 + 5.0 + 50.0)
    text = "\n".join(h.render())
    assert "# HELP t_seconds test" in text
    assert "# TYPE t_seconds histogram" in text
    assert 't_seconds_bucket{le="0.1"} 1' in text
    assert 't_seconds_bucket{le="+Inf"} 5' in text
    assert "t_seconds_count 5" in text
    assert "t_seconds_sum" in text
    # labelled render nests the worker label before le
    labelled = "\n".join(h.render('worker="w0"'))
    assert 't_seconds_bucket{worker="w0",le="+Inf"} 5' in labelled
    assert 't_seconds_count{worker="w0"} 5' in labelled


def test_histogram_weighted_observe_and_reset():
    h = Histogram("x", "x", buckets=(1.0,))
    h.observe(0.5, n=3)
    assert h.count == 3
    assert h.sum == pytest.approx(1.5)
    h.observe(float("nan"))          # ignored, never corrupts the series
    h.observe(0.5, n=0)
    assert h.count == 3
    h.reset()
    assert h.count == 0 and h.snapshot()["counts"] == [0, 0]


def test_histogram_percentile_interpolation():
    h = Histogram("p", "p", buckets=(0.1, 1.0, 10.0))
    assert h.percentile(0.5) is None  # empty
    for _ in range(10):
        h.observe(0.5)                # all in the (0.1, 1.0] bucket
    p50 = h.percentile(0.5)
    assert 0.1 < p50 <= 1.0
    # +Inf observations clamp to the top finite edge
    h2 = Histogram("q", "q", buckets=(1.0,))
    h2.observe(100.0)
    assert h2.percentile(0.99) == 1.0
    # snapshot round-trips through JSON (the ForwardPassMetrics path)
    snap = json.loads(json.dumps(h.snapshot()))
    assert percentile_from_snapshot(snap, 0.5) == pytest.approx(p50)


def test_weighted_percentile():
    assert weighted_percentile([], 0.5) is None
    pairs = [(0.010, 1), (0.002, 8), (0.030, 1)]
    assert weighted_percentile(pairs, 0.5) == pytest.approx(0.002)
    assert weighted_percentile(pairs, 1.0) == pytest.approx(0.030)


def test_registry_render_and_snapshot():
    reg = request_histograms(TelemetryRegistry(), engine=True)
    names = set(reg.snapshot())
    assert names == {
        "dynamo_request_ttft_seconds", "dynamo_request_itl_seconds",
        "dynamo_request_e2e_seconds", "dynamo_request_queue_seconds",
        "dynamo_engine_round_seconds",
    }
    reg.get("dynamo_request_ttft_seconds").observe(0.2)
    text = reg.render()
    assert "# TYPE dynamo_request_ttft_seconds histogram" in text
    assert "dynamo_request_ttft_seconds_count 1" in text
    # snapshots carry the help text for remote rendering
    assert reg.snapshot()["dynamo_request_itl_seconds"]["help"]


# ---------------------------------------------------------------------------
# flight recorder

def test_flight_recorder_ring_wraparound():
    f = FlightRecorder(capacity=8)
    for i in range(20):
        f.record("round", n=i)
    assert len(f) == 8
    assert f.recorded_total == 20
    events = f.snapshot()
    assert [e["n"] for e in events] == list(range(12, 20))  # oldest->newest
    assert [e["seq"] for e in events] == list(range(12, 20))
    assert all(e["kind"] == "round" and "ts" in e for e in events)


def test_flight_recorder_exactly_full():
    """The exactly-capacity boundary: _next has wrapped to 0 but the
    ring is full, not empty."""
    f = FlightRecorder(capacity=4)
    for i in range(4):
        f.record("round", n=i)
    assert [e["n"] for e in f.snapshot()] == [0, 1, 2, 3]
    assert len(f) == 4


def test_flight_recorder_dump_logs_events():
    import logging

    f = FlightRecorder(capacity=4)
    f.record("round", slots=[0, 1])
    records = []

    class _Sink(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    log = logging.getLogger("test_flight_dump")
    log.addHandler(_Sink())
    log.setLevel(logging.ERROR)
    f.dump(log, reason="boom")
    assert any("boom" in m for m in records)
    assert any("'slots': [0, 1]" in m for m in records)


# ---------------------------------------------------------------------------
# trace store

def test_trace_store_lifecycle_and_bounds():
    store = TraceStore(max_completed=3)
    tr = store.start("r1")
    assert store.has_active("r1")
    tr.add(Span(name="tokenize", start_s=1.0, duration_s=0.1))
    assert store.add_span("r1", Span(name="route", start_s=1.1))
    assert not store.add_span("missing", Span(name="x", start_s=0.0))
    store.merge("r1", [{"name": "queue", "start_s": 1.2,
                        "duration_s": 0.05}])
    done = store.finish("r1")
    assert done is not None and done.finished
    assert not store.has_active("r1")
    assert store.get("r1").span_names() == ["tokenize", "route", "queue"]
    # completed ring evicts oldest
    for i in range(5):
        store.record_remote(f"x{i}", [{"name": "prefill", "start_s": 0.0}])
    assert store.get("r1") is None
    assert store.recent_ids() == ["x2", "x3", "x4"]


def test_trace_alias_routes_choice_spans_to_parent():
    """n>1 fanout: per-choice request ids alias onto the parent trace,
    so route spans land on one tree and the engine's has_active check
    sees the frontend as the owner."""
    store = TraceStore()
    store.start("parent")
    store.alias("choice-1", "parent")
    assert store.has_active("choice-1")
    assert store.add_span("choice-1", Span(name="route", start_s=1.0))
    tr = store.finish("parent")
    assert tr.span_names() == ["route"]
    # aliases die with the parent
    assert not store.has_active("choice-1")
    assert not store.add_span("choice-1", Span(name="x", start_s=2.0))


def test_span_tree_serialization():
    parent = Span(name="prefill", start_s=10.0, duration_s=0.5,
                  attrs={"slot": 3},
                  children=[Span(name="g2_onboard", start_s=10.1,
                                 duration_s=0.2, attrs={"blocks": 4})])
    d = json.loads(json.dumps(parent.to_dict()))
    back = Span.from_dict(d)
    assert back.name == "prefill" and back.attrs == {"slot": 3}
    assert back.children[0].name == "g2_onboard"
    assert back.children[0].attrs == {"blocks": 4}


# ---------------------------------------------------------------------------
# trace context round-trip through the runtime protocol

class _SpanStubEngine:
    """Engine yielding a token then a finishing output whose annotations
    carry worker-side spans + timing — the remote-worker wire shape."""

    async def generate(self, request):
        import time as _t

        t0 = _t.time()
        yield LLMEngineOutput(token_ids=[1])
        yield LLMEngineOutput(
            token_ids=[2], finish_reason=FinishReason.EOS,
            annotations={
                "timing": {"ttft_s": 0.01, "itl_p50_s": 0.002,
                           "itl_p95_s": 0.004, "e2e_s": 0.1,
                           "queue_s": 0.001},
                "trace": {"spans": [
                    {"name": "queue", "start_s": t0, "duration_s": 0.001},
                    {"name": "prefill", "start_s": t0 + 0.001,
                     "duration_s": 0.05, "attrs": {"slot": 0}},
                    {"name": "decode_round", "start_s": t0 + 0.06,
                     "duration_s": 0.004, "attrs": {"tokens": 2}},
                ]},
            },
        )


async def test_trace_roundtrip_through_runtime_protocol():
    """Frontend-minted trace + worker spans over the real TCP framing:
    the spans survive serve_engine's to_dict -> frame -> from_dict and
    merge into the frontend's span tree keyed by request_id."""
    from dynamo_tpu.protocols.common import PreprocessedRequest
    from dynamo_tpu.runtime.endpoint import EndpointServer, call_endpoint
    from dynamo_tpu.runtime.remote_engine import engine_handler

    server = EndpointServer(engine_handler(_SpanStubEngine()))
    host, port = await server.start()
    try:
        import time as _t

        rid = "trace-rt-1"
        TRACES.start(rid)
        TRACES.add_span(rid, span_now("tokenize", _t.monotonic(), tokens=3))
        req = PreprocessedRequest(token_ids=[1, 2, 3], request_id=rid)
        toks = []
        async for item in call_endpoint(
            host, port, req.to_dict(), request_id=rid
        ):
            out = LLMEngineOutput.from_dict(item)
            toks.extend(out.token_ids)
            spans = (out.annotations.get("trace") or {}).get("spans")
            if spans:
                TRACES.merge(rid, spans)
        tr = TRACES.finish(rid)
        assert toks == [1, 2]
        names = tr.span_names()
        assert names[0] == "tokenize"
        assert {"queue", "prefill", "decode_round"} <= set(names)
        tree = tr.to_dict()
        prefill = next(s for s in tree["spans"] if s["name"] == "prefill")
        assert prefill["attrs"] == {"slot": 0}
    finally:
        await server.stop()
        TRACES.clear()


def test_request_stats_reads_timing_annotation():
    from dynamo_tpu.sdk import request_stats

    outs = [
        LLMEngineOutput(token_ids=[1, 2]),
        LLMEngineOutput(
            token_ids=[], finish_reason=FinishReason.EOS,
            annotations={"timing": {
                "ttft_s": 0.05, "itl_p50_s": 0.002, "itl_p95_s": 0.01,
                "e2e_s": 0.5, "queue_s": 0.003,
            }},
        ),
    ]
    st = request_stats(outs)
    assert st.ttft_s == pytest.approx(0.05)
    assert st.itl_p50_s == pytest.approx(0.002)
    assert st.itl_p95_s == pytest.approx(0.01)
    assert st.e2e_s == pytest.approx(0.5)
    assert st.queue_s == pytest.approx(0.003)


# ---------------------------------------------------------------------------
# frontend e2e: span tree retrievable, /metrics histograms populated

@pytest.fixture(scope="module")
def tiny_routed_manager():
    """Tiny TpuEngine behind a KvPushRouter (so the route span records)
    behind a ModelChain — the full in-process serving stack."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig

    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64, page_size=16, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    engine = TpuEngine(cfg, ecfg, mesh_config=MeshConfig(tp=1))
    router = KvPushRouter(KvRouter(block_size=16), workers={1: engine})
    tok = make_test_tokenizer(WORDS)
    chain = ModelChain(
        name="tiny",
        preprocessor=OpenAIPreprocessor(tokenizer=tok, model_name="tiny"),
        engine=router,
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    yield manager


async def _with_client(svc):
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    return client


async def test_frontend_span_tree_and_histograms(tiny_routed_manager):
    TRACES.clear()
    svc = HttpService(tiny_routed_manager)
    client = await _with_client(svc)
    completion_tokens = 0
    rids = []
    metrics_events = []
    for _ in range(2):
        r = await client.post(
            "/v1/chat/completions",
            json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello world"}],
                "max_tokens": 8,
                "ignore_eos": True,
                "stream": True,
                "stream_options": {"include_usage": True},
                "nvext": {"annotations": ["llm_metrics"]},
            },
        )
        assert r.status == 200
        rid = r.headers["X-Request-Id"]
        rids.append(rid)
        dec = SseDecoder()
        events = []
        async for chunk in r.content.iter_any():
            events.extend(dec.feed(chunk))
        for e in events[:-1]:
            body = e.json()
            if body.get("usage"):
                completion_tokens += body["usage"]["completion_tokens"]
            if body.get("nvext", {}).get("annotation") == "llm_metrics":
                metrics_events.append(body["nvext"]["metrics"])

    # --- span tree: tokenize -> route -> queue -> prefill -> decode ---
    for rid in rids:
        tr = await client.get(f"/debug/trace/{rid}")
        assert tr.status == 200
        tree = await tr.json()
        assert tree["trace_id"] == rid and tree["finished"]
        names = [s["name"] for s in tree["spans"]]
        for expected in ("tokenize", "route", "queue", "prefill",
                         "decode_round"):
            assert expected in names, (expected, names)
        route = next(s for s in tree["spans"] if s["name"] == "route")
        assert "overlap_blocks" in route["attrs"]
    idx = await client.get("/debug/trace")
    assert set(rids) <= set((await idx.json())["recent"])
    missing = await client.get("/debug/trace/nope")
    assert missing.status == 404

    # --- finishing llm_metrics annotation surfaces ITL p50/p95 ---
    assert len(metrics_events) == 2
    for m in metrics_events:
        assert m["ttft_s"] is not None
        assert m["itl_p50_s"] is not None
        assert m["itl_p95_s"] is not None
        assert m["itl_p95_s"] >= m["itl_p50_s"]

    # --- /metrics histograms: counts match requests/tokens served ---
    mr = await client.get("/metrics")
    text = await mr.text()
    assert "# TYPE dynamo_request_ttft_seconds histogram" in text
    assert "# TYPE dynamo_request_itl_seconds histogram" in text
    assert "dynamo_request_ttft_seconds_count 2" in text
    # the engine emits the first token alone, so the frontend observes
    # exactly tokens-1 inter-token gaps per request
    assert (f"dynamo_request_itl_seconds_count "
            f"{completion_tokens - 2}") in text
    assert "dynamo_request_e2e_seconds_count 2" in text

    # --- /debug/flight: the router is not an engine, but the worker
    # behind it records; the frontend aggregates engines exposing one ---
    fl = await client.get("/debug/flight")
    assert fl.status == 200  # router chain: no flight attr -> empty dict
    await client.close()
    TRACES.clear()


async def test_frontend_unary_trace_and_ttft(tiny_routed_manager):
    TRACES.clear()
    svc = HttpService(tiny_routed_manager)
    client = await _with_client(svc)
    r = await client.post(
        "/v1/completions",
        json={"model": "tiny", "prompt": "hello world", "max_tokens": 4,
              "ignore_eos": True},
    )
    assert r.status == 200
    rid = r.headers["X-Request-Id"]
    tr = await client.get(f"/debug/trace/{rid}")
    assert tr.status == 200
    names = [s["name"] for s in (await tr.json())["spans"]]
    assert "tokenize" in names and "prefill" in names
    mtext = await (await client.get("/metrics")).text()
    assert "dynamo_request_ttft_seconds_count 1" in mtext
    await client.close()
    TRACES.clear()


async def test_system_server_debug_endpoints():
    """Per-worker surface: /debug/flight serves the engine ring and
    /debug/trace serves the worker-local store."""
    from dynamo_tpu.runtime.system_server import SystemServer

    class _Eng:
        flight = FlightRecorder(capacity=4)

    _Eng.flight.record("round", slots=[0], dispatch_ms=1.0)
    TRACES.record_remote("w-req", [{"name": "queue", "start_s": 1.0,
                                    "duration_s": 0.5}])
    srv = await SystemServer(_Eng(), host="127.0.0.1", port=0,
                             worker_id="w7").start()
    try:
        import aiohttp

        async with aiohttp.ClientSession() as sess:
            async with sess.get(
                f"http://127.0.0.1:{srv.port}/debug/flight"
            ) as resp:
                body = await resp.json()
                assert body["worker_id"] == "w7"
                assert body["events"][0]["kind"] == "round"
            async with sess.get(
                f"http://127.0.0.1:{srv.port}/debug/trace/w-req"
            ) as resp:
                assert resp.status == 200
                assert (await resp.json())["spans"][0]["name"] == "queue"
    finally:
        await srv.stop()
        TRACES.clear()


async def test_engine_round_histogram_and_flight(tiny_routed_manager):
    """The engine-side series: queue/round histograms populate and the
    flight ring records prefill + round dispatches."""
    chain = tiny_routed_manager.get("tiny")
    eng = chain.engine.workers[1]
    snap = eng.telemetry.snapshot()
    assert snap["dynamo_engine_round_seconds"]["count"] > 0
    assert snap["dynamo_request_queue_seconds"]["count"] > 0
    kinds = {e["kind"] for e in eng.flight.snapshot()}
    assert "round" in kinds
    ev = eng.flight.snapshot()[-1]
    assert "dispatch_ms" in ev and "slots" in ev
