"""Performance-attribution plane tests (telemetry/prof.py + timeline).

Pins the tentpole invariants: the flat switch model attributes host
round time with self-coverage ~1.0 by construction; the always-on
instrumentation costs within 5% of the disabled engine's steady-decode
wall; the SLO burn-rate math interpolates histogram CDFs correctly; the
``--dispatch-budget`` tool emits a ``host_breakdown`` keyed by the full
segment enum; and the timeline exporter turns a real disagg request
(span tree + host rounds + kv_transfer stream events) into parseable
Chrome Trace Event Format JSON.
"""
import asyncio
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.telemetry.metrics import Histogram
from dynamo_tpu.telemetry.prof import (
    PROF,
    SEGMENTS,
    ProfRegistry,
    RoundProf,
    frac_over_target,
)

PS = 16
TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _engine(**kw) -> TpuEngine:
    base = dict(
        num_pages=128, page_size=PS, max_pages_per_seq=16,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32",
    )
    base.update(kw)
    return TpuEngine(ModelConfig.tiny(dtype="float32"),
                     EngineConfig(**base),
                     mesh_config=MeshConfig(tp=1))


# ---- RoundProf: the flat switch model --------------------------------


def test_roundprof_segment_sums_equal_wall():
    p = RoundProf()
    p.begin_round()
    p.enter(SEGMENTS.index("intake"))
    time.sleep(0.002)
    p.enter(SEGMENTS.index("dispatch"))
    time.sleep(0.003)
    p.end_round()
    assert p.rounds == 1
    t = p.totals()
    # self-coverage == 1.0 by construction: every elapsed slice is
    # charged to exactly one segment
    assert sum(t["segments"].values()) == pytest.approx(t["wall_s"])
    assert p.coverage() == pytest.approx(1.0)
    assert t["segments"]["intake"] >= 0.002
    assert t["segments"]["dispatch"] >= 0.003
    assert set(t["segments"]) == set(SEGMENTS)


def test_roundprof_push_restores_nested_segment():
    p = RoundProf()
    p.begin_round()
    p.enter(SEGMENTS.index("fetch"))
    time.sleep(0.001)
    prev = p.push(SEGMENTS.index("annotate"))
    time.sleep(0.002)
    p.enter(prev)
    time.sleep(0.001)
    p.end_round()
    t = p.totals()["segments"]
    assert t["annotate"] >= 0.002
    assert t["fetch"] >= 0.002  # both slices around the nested push


def test_roundprof_disabled_is_noop():
    p = RoundProf(enabled=False)
    p.begin_round()
    p.enter(SEGMENTS.index("dispatch"))
    p.end_round()
    assert p.rounds == 0
    assert p.wall_total == 0.0
    assert p.recent() == [] and p.drain() == []
    # summary still renders (the /debug/prof payload for an off engine)
    assert p.summary()["enabled"] is False


def test_roundprof_idle_rounds_not_recorded():
    p = RoundProf()
    p.begin_round()
    p.enter(SEGMENTS.index("intake"))
    p.end_round(record=False)
    assert p.rounds == 0 and p.recent() == [] and p.drain() == []
    p.begin_round()
    p.enter(SEGMENTS.index("intake"))
    p.end_round(record=True)
    assert p.rounds == 1 and len(p.recent()) == 1


def test_roundprof_ring_and_drain_bounded():
    p = RoundProf()
    for _ in range(p.RING + 50):
        p.begin_round()
        p.end_round()
    assert len(p.recent(10_000)) == p.RING
    drained = p.drain()
    assert len(drained) == p.RING
    assert p.drain() == []  # drain empties the unfolded buffer
    assert p.rounds == p.RING + 50  # cumulative counters keep counting


# ---- SLO burn-rate math ----------------------------------------------


def _snap(values, buckets):
    h = Histogram("x", "x", buckets)
    for v in values:
        h.observe(v)
    return h.snapshot()


def test_frac_over_target_edges_and_interpolation():
    assert frac_over_target(None, 0.5) == 0.0
    assert frac_over_target({}, 0.5) == 0.0
    b = (0.5, 1.0)
    assert frac_over_target(_snap([0.1] * 10, b), 0.5) == 0.0
    assert frac_over_target(_snap([2.0] * 10, b), 1.5) == \
        pytest.approx(1.0)
    # 10 observations in the (1.0, 2.0] bucket of buckets (1, 2); a
    # 1.5 target linearly splits the bucket: half the mass is over
    assert frac_over_target(_snap([1.2] * 10, (1.0, 2.0)), 1.5) == \
        pytest.approx(0.5)
    # mixed: 98 under, 2 over a target sitting exactly on an edge
    snap = _snap([0.1] * 98 + [0.9] * 2, (0.5, 1.0))
    assert frac_over_target(snap, 0.5) == pytest.approx(0.02)


def test_burn_rate_gauges_fold_and_render():
    reg = ProfRegistry()
    reg.configure(ttft_target_s=0.5, itl_target_s=0.05, objective=0.99)
    ttft = _snap([0.1] * 98 + [0.9] * 2, (0.5, 1.0))
    itl = _snap([0.01] * 100, (0.05, 0.1))
    burn = reg.fold_burn_rates(ttft, itl)
    # 2% over target / 1% error budget = burning 2x the sustainable rate
    assert burn["ttft"] == pytest.approx(2.0)
    assert burn["itl"] == pytest.approx(0.0)
    assert reg.burn_rates() == burn
    text = reg.render()
    assert "# TYPE dynamo_slo_ttft_burn_rate gauge" in text
    assert "dynamo_slo_ttft_burn_rate 2.0" in text
    # one family head, one labelled series per segment
    assert text.count("# TYPE dynamo_host_round_seconds histogram") == 1
    for s in SEGMENTS:
        assert f'segment="{s}"' in text


def test_registry_fold_observes_per_segment():
    reg = ProfRegistry()
    p = RoundProf()
    p.begin_round()
    p.enter(SEGMENTS.index("dispatch"))
    time.sleep(0.001)
    p.end_round()
    reg.fold(p)
    snap = reg.snapshot()
    assert snap["dispatch"]["count"] == 1
    assert snap["dispatch"]["sum"] >= 0.001
    assert snap["intake"]["count"] == 0
    assert reg.coverage_ratio() == pytest.approx(1.0)
    reg.fold(p)  # second fold: nothing new to drain
    assert reg.snapshot()["dispatch"]["count"] == 1


# ---- engine integration ----------------------------------------------


async def _run_wave(eng, prompts, osl):
    async def one(p):
        async for _ in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=osl,
                                           ignore_eos=True),
        )):
            pass

    await asyncio.gather(*[one(p) for p in prompts])


async def test_engine_attribution_coverage_and_host_budget():
    """Tier-1 pins: a served workload attributes its host time across
    the real segments with self-coverage >= 0.9, folds into the global
    PROF registry at the publish cadence, and the steady-decode host
    budget stays under a (generous, tiny-harness) per-round ceiling."""
    PROF.reset()
    eng = _engine()
    eng.start()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, 48).tolist() for _ in range(4)]
    await _run_wave(eng, prompts, 8)       # warmup: compiles
    t0 = eng.prof.totals()
    await _run_wave(eng, prompts, 48)
    await eng.stop()

    t1 = eng.prof.totals()
    rounds = t1["rounds"] - t0["rounds"]
    wall = t1["wall_s"] - t0["wall_s"]
    assert rounds >= 10
    assert eng.prof.coverage() >= 0.9
    seg = {s: t1["segments"][s] - t0["segments"][s] for s in SEGMENTS}
    # the hot segments of a decode-heavy workload actually got charged
    for s in ("dispatch", "fetch", "admit", "slot_scan"):
        assert seg[s] > 0.0, seg
    # whole-run host tripwire: on the CPU harness the admit segment
    # carries the blocking prefill compute itself, so exclude it here
    # (the steady-decode budget is pinned in the A/B test below);
    # 50 ms/round is the "something pathological landed in the host
    # loop" ceiling, not a perf target
    assert (wall - seg["admit"]) / rounds <= 0.050, (wall, rounds, seg)
    # /debug/prof payload shape
    s = eng.prof.summary(top=3)
    assert len(s["segments"]) == 3
    assert s["coverage_ratio"] >= 0.9
    assert s["segments"][0]["total_s"] >= s["segments"][1]["total_s"]
    # the publish-cadence fold populated the process-global registry
    snap = PROF.snapshot()
    assert sum(h["count"] for h in snap.values()) > 0
    assert set(PROF.burn_rates()) == {"ttft", "itl"}
    PROF.reset()


async def _steady_round_wall_ms(eng, repeats=2) -> float:
    """Min per-round wall (ms) over ``repeats`` steady-decode windows,
    same window mechanics as tests/test_dispatch_budget.py."""
    rng = np.random.RandomState(0)
    n_req, osl = 4, 64
    prompts = [rng.randint(1, 256, 48).tolist() for _ in range(n_req)]
    await _run_wave(eng, prompts, 8)  # warmup: compiles
    best = None
    for _ in range(repeats):
        progress = [0] * n_req

        async def one(i):
            async for out in eng.generate(PreprocessedRequest(
                token_ids=list(prompts[i]),
                stop_conditions=StopConditions(max_tokens=osl,
                                               ignore_eos=True),
            )):
                progress[i] += len(out.token_ids)

        tasks = [asyncio.ensure_future(one(i)) for i in range(n_req)]
        while not all(p >= 4 for p in progress):
            await asyncio.sleep(0.005)
        d0 = dict(eng.dispatch_counts)
        t0 = time.monotonic()
        while not any(p >= osl - 20 for p in progress):
            await asyncio.sleep(0.005)
        dt = time.monotonic() - t0
        d1 = dict(eng.dispatch_counts)
        await asyncio.gather(*tasks)
        rounds = (d1.get("round", 0) + d1.get("round_seal", 0)
                  - d0.get("round", 0) - d0.get("round_seal", 0))
        if rounds > 0:
            w = dt / rounds * 1e3
            best = w if best is None else min(best, w)
    return best


async def test_attribution_overhead_within_5pct():
    """The always-on claim: attribution ON vs OFF steady-decode
    per-round wall within 5% (plus a small absolute allowance for
    shared-CI scheduling noise — the instrumentation itself is ~15
    monotonic() calls, single-digit µs, per round)."""
    walls = {}
    for mode in (True, False):
        eng = _engine(prof_attribution=mode)
        eng.start()
        walls[mode] = await _steady_round_wall_ms(eng)
        await eng.stop()
    assert walls[True] is not None and walls[False] is not None
    assert walls[True] <= walls[False] * 1.05 + 0.3, walls
    # steady-decode host budget pin: the generous tiny-harness ceiling
    # (typical ~1-5 ms/round on CPU; regressions land well above)
    assert walls[True] <= 50.0, walls


def test_disabled_engine_records_nothing():
    eng = _engine(prof_attribution=False)
    assert eng.prof.enabled is False
    assert eng.prof.totals()["rounds"] == 0


# ---- profile_round --dispatch-budget tool contract -------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_profile_round_dispatch_budget_host_breakdown(capsys):
    """The tool's JSON line carries a host_breakdown keyed by the FULL
    segment enum (the contract bench.py and /debug/prof share) and a
    self-coverage >= 0.9."""
    mod = _load_tool("profile_round")
    assert mod._dispatch_budget_mode(2, 16, "none") == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["mode"] == "dispatch-budget"
    assert set(out["host_breakdown"]) == set(SEGMENTS)
    assert out["host_prof_rounds"] >= 1
    assert out["host_prof_coverage"] >= 0.9
    assert all(v >= 0.0 for v in out["host_breakdown"].values())


# ---- timeline export: disagg request -> Chrome trace JSON ------------


async def test_disagg_request_timeline_chrome_trace():
    """The exporter acceptance: one chunk-streamed disagg request's
    span tree + host-round records + kv_transfer stream events build a
    json-round-trippable Chrome trace with span events, round segments,
    and >= 1 kv_transfer stream event."""
    from dataclasses import replace

    from dynamo_tpu.disagg import (
        DisaggConfig,
        DisaggConfigWatcher,
        DisaggDecodeEngine,
        PrefillWorker,
    )
    from dynamo_tpu.kv_transfer import (
        BlocksetDescriptor,
        BlockTransferServer,
        KvCacheLayout,
        publish_descriptor,
    )
    from dynamo_tpu.models import llama
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store import serve_store
    from dynamo_tpu.telemetry.timeline import FRAME_SEND, STREAM_EVENTS

    STREAM_EVENTS.clear()
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    ecfg = EngineConfig(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )

    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    decode_inner = TpuEngine(cfg, replace(ecfg, worker_id="dec_tl"),
                             params=params, mesh_config=MeshConfig(tp=1))
    conf = await DisaggConfigWatcher(
        rt.kv, "tl",
        default=DisaggConfig(max_local_prefill_length=PS,
                             max_prefill_queue_size=4),
    ).start()
    decode = DisaggDecodeEngine(
        decode_inner, rt, namespace="tl", worker_id="dec_tl", conf=conf,
        prefill_timeout_s=30.0,
    )
    srv = BlockTransferServer(
        read_fn=decode_inner.export_pages, write_fn=decode.guarded_import,
    )
    host, xport = await srv.start()
    await publish_descriptor(rt.kv, "tl", BlocksetDescriptor(
        worker_id="dec_tl", host=host, port=xport,
        layout=KvCacheLayout(cfg.num_layers, cfg.num_kv_heads, PS,
                             cfg.head_dim, "float32"),
    ))
    pre_eng = TpuEngine(
        cfg, replace(ecfg, worker_id="pre_tl", kv_transfer_chunk_pages=2),
        params=params, mesh_config=MeshConfig(tp=1),
    )
    pworker = await PrefillWorker(
        rt, pre_eng, namespace="tl", poll_timeout_s=0.2,
    ).start()
    try:
        finishing = None
        async for out in decode.generate(PreprocessedRequest(
            token_ids=list(range(1, 114)),  # 7 blocks -> >= 3 frames
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
        )):
            if out.finish_reason is not None:
                finishing = out
        assert decode.remote_prefills == 1
        spans = (finishing.annotations.get("trace") or {}).get("spans", [])
        assert spans
        stream = STREAM_EVENTS.snapshot()
        assert any(e["kind"] == FRAME_SEND for e in stream)

        # the same assembly tools/trace_export.py drives: a pre-merged
        # bundle document -> Chrome trace
        te = _load_tool("trace_export")
        doc = {
            "trace": {"trace_id": "req-tl", "spans": spans},
            "flight": decode_inner.flight.snapshot(),
            "stream": stream,
            "rounds": [[r[0], r[1], list(r[2])]
                       for r in decode_inner.prof.recent(16)],
        }
        chrome = json.loads(json.dumps(te.build(doc)))

        assert chrome["displayTimeUnit"] == "ms"
        evs = chrome["traceEvents"]
        for ev in evs:
            assert ev["ph"] in ("X", "i", "M"), ev
            assert isinstance(ev["pid"], int)
            assert "name" in ev
            if ev["ph"] == "X":
                assert isinstance(ev["ts"], int) and ev["dur"] >= 1, ev
        names = {e["name"] for e in evs if e.get("cat") == "span"}
        assert "disagg_kv_transfer" in names
        assert any(e["name"] == "host_round" for e in evs)
        assert any(e.get("cat") == "round_segment" for e in evs)
        kv = [e for e in evs if e.get("cat") == "kv_stream"]
        assert len(kv) >= 1
        assert any(e["name"] == FRAME_SEND for e in kv)
    finally:
        await pworker.stop()
        await srv.stop()
        await conf.stop()
        await decode.stop()
        await pre_eng.stop()
        await rt.close()
        server.close()
        STREAM_EVENTS.clear()
