"""Model resolution tests (reference local_model.rs:39): local dir, GGUF
file, cached hub id, and the zero-egress error path."""
import os

import pytest

from dynamo_tpu.model_resolver import resolve_model


def test_local_dir(tmp_path):
    d = tmp_path / "model"
    d.mkdir()
    r = resolve_model(str(d))
    assert r.kind == "dir" and r.path == str(d)


def test_gguf_file(tmp_path):
    p = tmp_path / "m.gguf"
    p.write_bytes(b"GGUF")
    r = resolve_model(str(p))
    assert r.kind == "gguf"


def test_cached_hub_id(tmp_path, monkeypatch):
    snap = tmp_path / "hub" / "models--org--name" / "snapshots" / "abc123"
    snap.mkdir(parents=True)
    (snap / "config.json").write_text("{}")
    monkeypatch.setenv("HF_HUB_CACHE", str(tmp_path / "hub"))
    r = resolve_model("org/name")
    assert r.kind == "dir" and r.path == str(snap)


def test_uncached_hub_id_errors_with_guidance(tmp_path, monkeypatch):
    monkeypatch.setenv("HF_HUB_CACHE", str(tmp_path / "empty"))
    monkeypatch.setenv("HF_HOME", str(tmp_path / "empty2"))
    with pytest.raises(FileNotFoundError, match="no egress"):
        resolve_model("org/missing-model")


def test_bogus_path_errors():
    with pytest.raises(FileNotFoundError, match="does not exist"):
        resolve_model("/no/such/dir")
