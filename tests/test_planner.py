"""Planner + metrics-exporter tests (reference planner_core.py:131-168
observe->decide->scale loop; components/metrics re-exporter).

Keystone e2e: a real planner over a real LocalConnector scales an actual
mocker-worker fleet 1 -> 3 -> 1 as synthetic load comes and goes, with the
load signal flowing worker -> store metrics plane -> planner.
"""
import asyncio
import sys

import pytest

from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.planner import LocalConnector, Planner, PlannerConfig
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store import serve_store


class FakeConnector:
    def __init__(self, n: int = 1):
        self.n = n
        self.calls: list[int] = []

    def current_replicas(self) -> int:
        return self.n

    async def set_replicas(self, n: int) -> None:
        self.calls.append(n)
        self.n = n


def metrics(worker, usage=0.0, waiting=0):
    return ForwardPassMetrics(
        worker_id=worker,
        worker_stats=WorkerStats(num_requests_waiting=waiting),
        kv_stats=KvStats(gpu_cache_usage_perc=usage),
    )


async def test_planner_decision_thresholds():
    server, store = await serve_store(port=0)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    conn = FakeConnector(2)
    planner = Planner(kv, conn, PlannerConfig(
        kv_usage_scale_up=0.8, kv_usage_scale_down=0.3,
        waiting_scale_up=4, min_replicas=1, max_replicas=4,
        stable_intervals=2,
    ))
    agg = planner.aggregator

    # in-band load: hold
    agg.update(metrics("w0", usage=0.5))
    assert planner.decide() == 2

    # high KV usage: scale up
    agg.update(metrics("w0", usage=0.9))
    assert planner.decide() == 3

    # deep queue alone: scale up
    agg.update(metrics("w0", usage=0.5, waiting=9))
    assert planner.decide() == 3

    # low load: downscale only after stable_intervals consecutive lows
    agg.update(metrics("w0", usage=0.1))
    assert planner.decide() == 2           # streak 1: hold
    assert planner.decide() == 1           # streak 2: down
    # clamped at min_replicas
    conn.n = 1
    assert planner.decide() == 1
    assert planner.decide() == 1

    # clamped at max_replicas
    conn.n = 4
    agg.update(metrics("w0", usage=0.95))
    assert planner.decide() == 4

    await kv.close()
    server.close()


@pytest.mark.asyncio_timeout(420)
async def test_planner_e2e_scales_mocker_fleet():
    """1 -> 3 -> 1 with REAL subprocess workers: load held open on the
    fleet pushes KV usage over the (low) threshold; the planner spawns
    CLI mocker workers; releasing the load shrinks the fleet."""
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    cp = f"127.0.0.1:{port}"

    worker_cmd = [
        sys.executable, "-m", "dynamo_tpu.cli", "run",
        "in=endpoint", "out=mocker",
        "--control-plane", cp, "--model-name", "pm",
        "--namespace", "plan", "--page-size", "4",
    ]
    conn = LocalConnector(worker_cmd)
    kv = await KvClient(port=port).connect()
    planner = Planner(kv, conn, PlannerConfig(
        adjustment_interval_s=1.0,
        kv_usage_scale_up=0.01,   # ANY active request triggers scale-up
        kv_usage_scale_down=0.005,
        waiting_scale_up=10_000,
        min_replicas=1, max_replicas=3, stable_intervals=2,
        metrics_stale_after_s=30.0,
    ))
    rt = await DistributedRuntime.connect(port=port)
    client = None
    stream = None
    try:
        await conn.set_replicas(1)
        await planner.start()
        client = await rt.namespace("plan").component("backend").endpoint(
            "generate"
        ).client()
        await client.wait_for_instances(1, timeout_s=90)

        # open-ended load: one long-running stream holds pages/slots
        stream = client.generate({
            "token_ids": list(range(1, 40)),
            "stop_conditions": {"max_tokens": 100000, "ignore_eos": True},
        })
        # consume slowly in the background so the request stays active
        async def sip():
            async for _ in stream:
                await asyncio.sleep(0.05)
        sip_task = asyncio.create_task(sip())

        # planner observes load -> scales to 3 (one step per interval)
        for _ in range(240):
            if conn.current_replicas() == 3:
                break
            await asyncio.sleep(0.5)
        assert conn.current_replicas() == 3
        await client.wait_for_instances(3, timeout_s=120)

        # release the load -> metrics decay -> back down to 1
        sip_task.cancel()
        aclose = getattr(stream, "aclose", None)
        if aclose:
            await aclose()
        stream = None
        for _ in range(360):
            if conn.current_replicas() == 1:
                break
            await asyncio.sleep(0.5)
        assert conn.current_replicas() == 1
    finally:
        await planner.stop()
        if stream is not None:
            aclose = getattr(stream, "aclose", None)
            if aclose:
                await aclose()
        if client is not None:
            await client.stop()
        await conn.shutdown()
        await rt.close()
        await kv.close()
        server.close()


async def test_metrics_exporter_prometheus():
    """components/metrics parity: load plane -> Prometheus text."""
    import aiohttp

    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.publisher import WorkerMetricsPublisher

    server, store = await serve_store(port=0)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    exp = await MetricsExporter(kv, host="127.0.0.1", port=0).start()

    wkv = await KvClient(port=port).connect()
    pub = WorkerMetricsPublisher(wkv, "w7", min_interval_s=0.0)
    pub.start()
    pub(metrics("w7", usage=0.42, waiting=3))
    await asyncio.sleep(0.3)

    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{exp.port}/metrics") as r:
            assert r.status == 200
            text = await r.text()
    assert 'dynamo_kv_usage_perc{worker="w7"} 0.42' in text
    assert 'dynamo_worker_waiting_requests{worker="w7"} 3' in text
    assert "dynamo_metrics_workers 1" in text

    await pub.stop()
    await exp.stop()
    await wkv.close()
    await kv.close()
    server.close()


async def test_system_server_per_worker():
    """Reference http_server.rs parity: each worker process exposes its
    own /metrics + /health."""
    import aiohttp

    from dynamo_tpu.mocker import MockerArgs, MockerEngine
    from dynamo_tpu.runtime.system_server import SystemServer

    eng = MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=4))
    srv = await SystemServer(eng, host="127.0.0.1", port=0,
                             worker_id="w9").start()
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{srv.port}/health") as r:
            body = await r.json()
            assert body["status"] == "ok" and body["worker_id"] == "w9"
        async with s.get(f"http://127.0.0.1:{srv.port}/metrics") as r:
            text = await r.text()
    assert "dynamo_system_uptime_seconds" in text
    assert 'dynamo_worker_total_slots{worker="w9"} 8' in text
    await srv.stop()
    await eng.stop()


@pytest.mark.asyncio_timeout(600)
async def test_planner_scales_multihost_engine_groups():
    """BASELINE config 4 x planner: DP replicas OF a cross-host engine.
    Each replica the planner adds is a 2-process lockstep group (leader
    in=endpoint + replay follower over one jax.distributed mesh); scale
    1 -> 2 under held load, then back to 1, with registrations following
    (VERDICT r4 #7: planner and multihost had never met)."""
    import os

    from dynamo_tpu.planner import MultihostLocalConnector

    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    cp = f"127.0.0.1:{port}"
    cmd = [
        sys.executable, "-m", "dynamo_tpu.cli", "run",
        "in=endpoint", "out=tpu", "--model-config", "tiny_wide",
        "--tensor-parallel-size", "4",
        "--num-nodes", "2", "--node-rank", "{rank}",
        "--leader-addr", "{coord}",
        "--control-plane", cp, "--namespace", "mhplan",
        "--component", "backend-r{replica}", "--model-name", "mh",
        "--page-size", "16", "--num-pages", "32",
        "--max-decode-slots", "2", "--cache-dtype", "float32",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    conn = MultihostLocalConnector(cmd, num_nodes=2, env=env)
    kv = await KvClient(port=port).connect()
    planner = Planner(kv, conn, PlannerConfig(
        adjustment_interval_s=1.0,
        kv_usage_scale_up=0.01,
        kv_usage_scale_down=0.005,
        waiting_scale_up=10_000,
        min_replicas=1, max_replicas=2, stable_intervals=2,
        metrics_stale_after_s=60.0,
    ))
    rt = await DistributedRuntime.connect(port=port)
    client = None
    sip_task = None
    try:
        await conn.set_replicas(1)
        await planner.start()
        client = await rt.namespace("mhplan").component(
            "backend-r0").endpoint("generate").client()
        # liveness-aware bring-up wait: if the spawned group dies (the
        # cross-host smoke can't run in every environment) fail in
        # seconds instead of burning the whole instance timeout
        deadline = asyncio.get_running_loop().time() + 180
        while True:
            assert conn.current_replicas() >= 1, \
                "multihost group died during bring-up"
            try:
                await client.wait_for_instances(1, timeout_s=2.0)
                break
            except TimeoutError:
                if asyncio.get_running_loop().time() > deadline:
                    raise

        stream = client.generate({
            "token_ids": list(range(1, 50)),
            "stop_conditions": {"max_tokens": 100000, "ignore_eos": True},
        })

        async def sip():
            async for _ in stream:
                await asyncio.sleep(0.05)

        sip_task = asyncio.create_task(sip())

        for _ in range(360):
            if conn.current_replicas() == 2:
                break
            await asyncio.sleep(0.5)
        assert conn.current_replicas() == 2
        # the new group registers as its own model instance
        for _ in range(240):
            regs = await kv.get_prefix("dynamo://mhplan/_models/mh/")
            if len(regs) >= 2:
                break
            await asyncio.sleep(0.5)
        assert len(await kv.get_prefix("dynamo://mhplan/_models/mh/")) == 2

        sip_task.cancel()
        try:
            await sip_task  # let the generator unwind before aclose
        except asyncio.CancelledError:
            pass
        sip_task = None
        aclose = getattr(stream, "aclose", None)
        if aclose:
            await aclose()
        for _ in range(360):
            if conn.current_replicas() == 1:
                break
            await asyncio.sleep(0.5)
        assert conn.current_replicas() == 1
    finally:
        await planner.stop()
        if sip_task is not None:
            sip_task.cancel()
        if client is not None:
            await client.stop()
        await conn.shutdown()
        await rt.close()
        await kv.close()
        server.close()
