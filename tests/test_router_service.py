"""Standalone router component tests (reference components/router
src/main.rs:53-77): routing as its own runtime service — callers query
find_best and direct-route themselves."""
import asyncio
import json

from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent,
    KvEventKind,
    StoredBlock,
)
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.router_service import RouterService
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.publisher import KV_EVENTS_TOPIC
from dynamo_tpu.runtime.remote_engine import serve_engine
from dynamo_tpu.runtime.store import serve_store
from dynamo_tpu.tokens import TokenBlockSequence

BS = 4


async def test_router_service_routes_and_follows_events():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]

    # two mocker workers on the watched endpoint
    rts, served = [], []
    for i in range(2):
        rt = await DistributedRuntime.connect(port=port)
        ep = rt.namespace("rs").component("backend").endpoint("generate")
        s = await serve_engine(
            ep, MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=BS)),
            worker_id=f"w{i}",
        )
        rts.append(rt)
        served.append(s)

    rt_router = await DistributedRuntime.connect(port=port)
    svc = await RouterService(
        rt_router, namespace="rs", component="backend",
        endpoint="generate", block_size=BS,
    ).start()

    rt_client = await DistributedRuntime.connect(port=port)
    try:
        # wait until the router sees both workers
        for _ in range(100):
            if len(svc.router.sequences._workers) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(svc.router.sequences._workers) == 2

        client = await rt_client.namespace("rs").component(
            "backend-router"
        ).endpoint("find_best").client()
        for _ in range(100):
            if client.instances:
                break
            await asyncio.sleep(0.02)

        async def find_best(tokens, rid="r1"):
            async for item in client.generate(
                {"token_ids": tokens, "request_id": rid}
            ):
                return item

        tokens = list(range(1, 13))  # 3 blocks
        out = await find_best(tokens)
        assert out["worker_id"] in {str(served[0].lease_id),
                                    str(served[1].lease_id)}
        assert out["overlap_blocks"] == 0

        # publish KV events claiming worker 0 holds this prefix; routing
        # must now prefer it with the right overlap count
        wid0 = str(served[0].lease_id)
        seq = TokenBlockSequence.from_tokens(tokens, BS, salt="")
        hashes = seq.block_hashes()
        parent = 0
        for i, h in enumerate(hashes):
            ev = KvCacheEvent(
                kind=KvEventKind.STORED, worker_id=wid0,
                parent_hash=parent,
                blocks=[StoredBlock(block_hash=h)],
            )
            await rt_client.kv.publish(
                f"{KV_EVENTS_TOPIC}.{wid0}", json.dumps(ev.to_dict())
            )
            parent = h
        for _ in range(100):
            if svc.router.indexer.total_blocks() >= 3:
                break
            await asyncio.sleep(0.02)

        # events settle asynchronously; poll until the routing reflects
        # the warm worker (slow-1-core-box tolerance)
        out2 = None
        for i in range(50):
            out2 = await find_best(tokens, rid=f"r2-{i}")
            if out2["overlap_blocks"] == 3:
                break
            await asyncio.sleep(0.05)
        assert out2["worker_id"] == wid0, out2
        assert out2["overlap_blocks"] == 3, out2
        assert svc.requests_routed >= 2
    finally:
        await svc.stop()
        await rt_client.close()
        await rt_router.close()
        for s in served:
            await s.shutdown()
        for rt in rts:
            await rt.close()
        server.close()
