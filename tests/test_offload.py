"""Host-DRAM KV offload tier tests (KVBM G2 — reference offload.rs:46-80).

Keystone: under HBM pressure, evicted prefix blocks survive in the host
tier; a re-sent prompt onboards them back instead of recomputing, and the
decode output stays bit-exact.
"""
import asyncio
from dataclasses import replace

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.offload import HostOffloadTier
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

PS = 16


# ---------------------------------------------------------------------------
# tier unit tests


def test_tier_put_lookup_lru():
    shape = (2, 2, 1, PS, 4)
    t = HostOffloadTier(3, shape, np.float32)
    data = np.arange(2 * 2 * 1 * 2 * PS * 4, dtype=np.float32).reshape(
        2, 2, 1, 2, PS, 4
    )
    assert t.put_batch([11, 12], [0, 11], data) == 2
    assert 11 in t and 12 in t
    run = t.lookup_run([11, 12, 13])
    assert run == [(11, 0), (12, 11)]
    got = t.gather([11, 12])
    np.testing.assert_array_equal(got, data)

    # LRU eviction: fill past capacity; oldest (11 was refreshed by the
    # lookup, so 12... also refreshed; insert 2 more evicts 11 then 12)
    one = data[:, :, :, :1]
    t.put_batch([13], [12], one)
    t.put_batch([14], [13], one)  # capacity 3: evicts LRU-oldest (11)
    assert 11 not in t and len(t) == 3
    # duplicate put refreshes, does not duplicate
    assert t.put_batch([13], [12], one) == 0
    assert len(t) == 3


def test_tier_lookup_stops_at_gap():
    t = HostOffloadTier(4, (2, 2, 1, PS, 4), np.float32)
    one = np.zeros((2, 2, 1, 1, PS, 4), np.float32)
    t.put_batch([1], [0], one)
    t.put_batch([3], [2], one)
    assert t.lookup_run([1, 2, 3]) == [(1, 0)]
    assert t.lookup_run([2, 3]) == []


# ---------------------------------------------------------------------------
# engine integration


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    # SMALL HBM pool (12 usable pages) + host tier: pressure evicts fast
    ecfg = EngineConfig(
        num_pages=13, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", host_offload_pages=16, offload_batch=8,
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def mk_engine(setup, **kw):
    cfg, ecfg, params = setup
    if kw:
        ecfg = replace(ecfg, **kw)
    return TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=6):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


async def test_offload_evict_onboard_bit_exact(setup):
    """Prefix evicted from HBM under pressure is re-served from the host
    tier: no recompute of those blocks, identical output."""
    eng = mk_engine(setup)
    prompt_a = list(range(1, 50))  # 3 complete blocks + tail

    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))

    out_a = await collect(eng, req_for(prompt_a))
    assert out_a == ref

    # wait for the async offload of A's parked blocks to land in G2
    for _ in range(200):
        if len(eng.offload) >= 3:
            break
        await asyncio.sleep(0.02)
    assert len(eng.offload) >= 3

    # pressure: different prompts large enough to evict A's blocks from HBM
    for base in (100, 200, 300):
        await collect(eng, req_for(list(range(base, base + 49))))
    from dynamo_tpu.tokens import TokenBlockSequence

    seq = TokenBlockSequence.from_tokens(prompt_a, PS, salt="")
    assert eng.allocator.cached_prefix_len(seq.block_hashes()[:3]) == 0, \
        "test premise: A's blocks must be evicted from HBM"

    # re-send A: blocks onboard from the host tier, output bit-exact
    hits_before = eng.offload.onboard_hits
    out_a2 = await collect(eng, req_for(prompt_a))
    assert out_a2 == ref
    assert eng.offload.onboard_hits - hits_before >= 3

    # tier metrics distinguish G1 vs G2
    m = eng.metrics()
    assert m.kv_stats.host_total_blocks == 16
    assert m.kv_stats.host_blocks >= 3
    assert m.kv_stats.host_onboard_hits >= 3
    await eng.stop()


async def test_offload_disabled_by_default(setup):
    eng = mk_engine(setup, host_offload_pages=0)
    assert eng.offload is None
    out = await collect(eng, req_for(list(range(1, 40))))
    assert len(out) == 6
    m = eng.metrics()
    assert m.kv_stats.host_total_blocks == 0
    await eng.stop()
