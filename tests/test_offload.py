"""Host-DRAM KV offload tier tests (KVBM G2 — reference offload.rs:46-80).

Keystone: under HBM pressure, evicted prefix blocks survive in the host
tier; a re-sent prompt onboards them back instead of recomputing, and the
decode output stays bit-exact.
"""
import asyncio
from dataclasses import replace

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.offload import DiskOffloadTier, HostOffloadTier
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

PS = 16


# ---------------------------------------------------------------------------
# tier unit tests


def test_tier_put_lookup_lru():
    shape = (2, 2, 1, PS, 4)
    t = HostOffloadTier(3, shape, np.float32)
    data = np.arange(2 * 2 * 1 * 2 * PS * 4, dtype=np.float32).reshape(
        2, 2, 1, 2, PS, 4
    )
    assert t.put_batch([11, 12], [0, 11], data) == 2
    assert 11 in t and 12 in t
    run = t.lookup_run([11, 12, 13])
    assert run == [(11, 0), (12, 11)]
    got = t.gather([11, 12])
    np.testing.assert_array_equal(got, data)

    # LRU eviction: fill past capacity; oldest (11 was refreshed by the
    # lookup, so 12... also refreshed; insert 2 more evicts 11 then 12)
    one = data[:, :, :, :1]
    t.put_batch([13], [12], one)
    t.put_batch([14], [13], one)  # capacity 3: evicts LRU-oldest (11)
    assert 11 not in t and len(t) == 3
    # duplicate put refreshes, does not duplicate
    assert t.put_batch([13], [12], one) == 0
    assert len(t) == 3


def test_tier_lookup_stops_at_gap():
    t = HostOffloadTier(4, (2, 2, 1, PS, 4), np.float32)
    one = np.zeros((2, 2, 1, 1, PS, 4), np.float32)
    t.put_batch([1], [0], one)
    t.put_batch([3], [2], one)
    assert t.lookup_run([1, 2, 3]) == [(1, 0)]
    assert t.lookup_run([2, 3]) == []


# ---------------------------------------------------------------------------
# G3 disk tier (reference storage/disk.rs:25, block_manager.rs:69-82)


def test_g2_eviction_spills_to_disk_and_run_spans_tiers(tmp_path):
    shape = (2, 2, 1, PS, 4)
    disk = DiskOffloadTier(4, shape, np.float32,
                           path=str(tmp_path / "g3.mmap"))
    t = HostOffloadTier(2, shape, np.float32, spill=disk)
    pages = [
        np.full((2, 2, 1, 1, PS, 4), float(i + 1), np.float32)
        for i in range(4)
    ]
    t.put_batch([1], [0], pages[0])
    t.put_batch([2], [1], pages[1])
    # capacity 2: inserting 3 evicts LRU hash 1 -> spilled to disk, not lost
    t.put_batch([3], [2], pages[2])
    assert 1 not in t._index and 1 in disk
    # a prefix run assembles across both tiers: 1 (disk), 2, 3 (RAM)
    run = t.lookup_run([1, 2, 3, 99])
    assert run == [(1, 0), (2, 1), (3, 2)]
    got = t.gather([1, 2, 3])
    np.testing.assert_array_equal(got[:, :, :, 0], pages[0][:, :, :, 0])
    np.testing.assert_array_equal(got[:, :, :, 2], pages[2][:, :, :, 0])
    # clear empties both tiers
    assert t.clear() == 3
    assert len(t) == 0 and len(disk) == 0
    disk.close()


def test_disk_tier_lru_and_persistence_within_session(tmp_path):
    shape = (2, 2, 1, PS, 4)
    disk = DiskOffloadTier(2, shape, np.float32,
                           path=str(tmp_path / "g3.mmap"))
    a = np.full(shape, 7.0, np.float32)
    b = np.full(shape, 8.0, np.float32)
    c_ = np.full(shape, 9.0, np.float32)
    disk.put_one(10, 0, a)
    disk.put_one(11, 10, b)
    disk.put_one(12, 11, c_)  # evicts 10 (capacity 2)
    assert 10 not in disk and 11 in disk and 12 in disk
    np.testing.assert_array_equal(disk.read_page(12), c_)
    disk.close()


def test_disk_tier_tempfile_cleanup():
    import os

    disk = DiskOffloadTier(1, (2, 2, 1, PS, 4), np.float32)
    disk.put_one(5, 0, np.zeros((2, 2, 1, PS, 4), np.float32))
    path = disk.path
    assert path is not None and os.path.exists(path)
    disk.close()
    assert not os.path.exists(path)


def test_engine_requires_g2_for_g3(setup):
    with pytest.raises(ValueError, match="requires host_offload_pages"):
        mk_engine(setup, host_offload_pages=0, disk_offload_pages=4)


async def test_disk_onboard_bit_exact(setup, tmp_path):
    """Multi-turn trace whose working set exceeds BOTH HBM and a tiny G2:
    prefix blocks cascade G1 -> G2 -> G3 and are re-served from DISK on a
    later turn, bit-exact (reference parity: storage/disk.rs tier)."""
    eng = mk_engine(setup, host_offload_pages=2, disk_offload_pages=16,
                    disk_offload_path=str(tmp_path / "g3.mmap"))
    prompt_a = list(range(1, 50))  # 3 complete blocks + tail

    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))
    out_a = await collect(eng, req_for(prompt_a))
    assert out_a == ref

    # wait for A's parked blocks to land in the host tiers
    for _ in range(200):
        if len(eng.offload) + len(eng.offload.spill) >= 3:
            break
        await asyncio.sleep(0.02)

    # pressure: push enough other prompts through that A's blocks are
    # evicted from HBM and G2 (capacity 2) spills them into G3
    for base in (100, 200, 300, 400):
        await collect(eng, req_for(list(range(base, base + 49))))
        await asyncio.sleep(0.05)
    from dynamo_tpu.tokens import TokenBlockSequence

    seq = TokenBlockSequence.from_tokens(prompt_a, PS, salt="")
    hashes = seq.block_hashes()[:3]
    assert eng.allocator.cached_prefix_len(hashes) == 0, \
        "test premise: A's blocks must be evicted from HBM"
    in_disk = sum(h in eng.offload.spill for h in hashes)
    assert in_disk >= 1, "test premise: G2 pressure must spill A to disk"

    out_a2 = await collect(eng, req_for(prompt_a))
    assert out_a2 == ref

    m = eng.metrics()
    assert m.kv_stats.disk_total_blocks == 16
    assert m.kv_stats.disk_blocks >= 1

    # clear_kv_blocks drops every tier
    n = await asyncio.to_thread(eng.clear_kv_blocks)
    assert n >= 3
    assert len(eng.offload) == 0 and len(eng.offload.spill) == 0
    await eng.stop()


# ---------------------------------------------------------------------------
# engine integration


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    # SMALL HBM pool (12 usable pages) + host tier: pressure evicts fast
    ecfg = EngineConfig(
        num_pages=13, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", host_offload_pages=16, offload_batch=8,
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def mk_engine(setup, **kw):
    cfg, ecfg, params = setup
    if kw:
        ecfg = replace(ecfg, **kw)
    return TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=6):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


async def test_offload_evict_onboard_bit_exact(setup):
    """Prefix evicted from HBM under pressure is re-served from the host
    tier: no recompute of those blocks, identical output."""
    eng = mk_engine(setup)
    prompt_a = list(range(1, 50))  # 3 complete blocks + tail

    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))

    out_a = await collect(eng, req_for(prompt_a))
    assert out_a == ref

    # wait for the async offload of A's parked blocks to land in G2
    for _ in range(200):
        if len(eng.offload) >= 3:
            break
        await asyncio.sleep(0.02)
    assert len(eng.offload) >= 3

    # pressure: different prompts large enough to evict A's blocks from HBM
    # (active requests hold no pool pages in the round-4 layout, so the
    # pressure must come entirely from committed prefix blocks: 4 prompts
    # x 3 blocks > the 12-page pool)
    for base in (100, 200, 300, 400):
        await collect(eng, req_for(list(range(base, base + 49))))
    from dynamo_tpu.tokens import TokenBlockSequence

    seq = TokenBlockSequence.from_tokens(prompt_a, PS, salt="")
    assert eng.allocator.cached_prefix_len(seq.block_hashes()[:3]) == 0, \
        "test premise: A's blocks must be evicted from HBM"

    # re-send A: blocks onboard from the host tier, output bit-exact
    hits_before = eng.offload.onboard_hits
    out_a2 = await collect(eng, req_for(prompt_a))
    assert out_a2 == ref
    assert eng.offload.onboard_hits - hits_before >= 3

    # tier metrics distinguish G1 vs G2
    m = eng.metrics()
    assert m.kv_stats.host_total_blocks == 16
    assert m.kv_stats.host_blocks >= 3
    assert m.kv_stats.host_onboard_hits >= 3
    await eng.stop()


async def test_offload_disabled_by_default(setup):
    eng = mk_engine(setup, host_offload_pages=0)
    assert eng.offload is None
    out = await collect(eng, req_for(list(range(1, 40))))
    assert len(out) == 6
    m = eng.metrics()
    assert m.kv_stats.host_total_blocks == 0
    await eng.stop()
