"""Multi-tenant serving plane (dynamo_tpu/tenancy/).

Keystones: (1) tenant identity is minted at the frontend and survives
every hop — request hints, the engine's quota check, the endpoint wire
error frame; (2) per-tenant budgets bounce the offending tenant with a
Retry-After derived from that tenant's OWN queue waits, while other
tenants keep flowing; (3) SFQ fair share lets a light tenant's fresh
arrival overtake a storming tenant's backlog; (4) adapter 0 is the
EXACT identity base model — a banked engine is greedy token-identical
to a bankless one, and mixed-adapter batches keep per-stream identity;
(5) aliasing variant names (symlinks, trailing slashes) resolve to ONE
shared weight load; (6) tools/tenant_stats.py's 0/1/2 exit contract.
"""
import asyncio
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.overload.deadline import apply_request_hints
from dynamo_tpu.overload.errors import EngineOverloadedError
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.tenancy import (
    DEFAULT_TENANT,
    TENANT,
    TenantQuotas,
    parse_tenant,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean_tenant_registry():
    TENANT.reset()
    yield
    TENANT.reset()


def req(prompt, max_tokens=8, tenant=None, **kw):
    r = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        **kw,
    )
    if tenant is not None:
        r.tenant = tenant
    return r


async def collect(eng, r):
    toks = []
    async for out in eng.generate(r):
        toks.extend(out.token_ids)
    return toks


# ---------------------------------------------------------------------------
# tenant minting: parse_tenant + apply_request_hints


def test_parse_tenant_sanitizes_and_defaults():
    assert parse_tenant(None) == DEFAULT_TENANT
    assert parse_tenant("") == DEFAULT_TENANT
    assert parse_tenant("   ") == DEFAULT_TENANT
    assert parse_tenant("acme") == "acme"
    assert parse_tenant("  acme  ") == "acme"
    # label-breaking characters are stripped, not escaped
    assert parse_tenant('ac"me\\x\n\r') == "acmex"
    assert parse_tenant('"\\') == DEFAULT_TENANT
    assert parse_tenant(123) == "123"
    assert len(parse_tenant("x" * 200)) == 64


def test_apply_request_hints_mints_tenant_header_over_body():
    pre = PreprocessedRequest(token_ids=[1])
    assert pre.tenant == DEFAULT_TENANT  # legacy traffic
    apply_request_hints(pre, nvext={"tenant": "body-co"})
    assert pre.tenant == "body-co"
    # a proxy-injected header wins over a stale client body
    apply_request_hints(pre, headers={"X-Tenant-Id": "edge-co"},
                        nvext={"tenant": "body-co"})
    assert pre.tenant == "edge-co"
    # malformed hints fall into the default tenant, never fail
    apply_request_hints(pre, nvext={"tenant": '"\\'})
    assert pre.tenant == DEFAULT_TENANT


# ---------------------------------------------------------------------------
# TenantQuotas arithmetic


def test_quotas_over_budget_at_cap_not_only_past_it():
    q = TenantQuotas(max_waiting_requests=2)
    assert not q.bounded or q.bounded  # bounded property exists
    assert q.bounded
    assert not q.over_budget(1, 0)
    assert q.over_budget(2, 0)  # >= semantics: AT the cap is over
    qt = TenantQuotas(max_waiting_prefill_tokens=100)
    assert not qt.over_budget(50, 99)
    assert qt.over_budget(0, 100)
    assert not TenantQuotas().bounded  # 0/0 = unbounded


def test_quotas_check_raises_with_tenant_and_retry_after():
    q = TenantQuotas(max_waiting_requests=1)
    q.check("acme", 0, 0)  # under budget: no-op
    with pytest.raises(EngineOverloadedError) as ei:
        q.check("acme", 1, 0)
    assert ei.value.tenant == "acme"
    assert ei.value.retry_after_s > 0


def test_retry_after_derives_from_the_tenants_own_waits():
    q = TenantQuotas(max_waiting_requests=4)
    for _ in range(10):
        q.note_queue_wait("storm", 2.0)
        q.note_queue_wait("calm", 0.01)
    assert q.queue_wait_p50("storm") == pytest.approx(2.0)
    # p50 x depth, clamped to [0.5, 30]
    assert q.retry_after_s("storm", 3) == pytest.approx(6.0)
    assert q.retry_after_s("storm", 100) == 30.0
    assert q.retry_after_s("calm", 3) == 0.5
    # no observations yet: the default per-request wait stands in
    assert q.retry_after_s("fresh", 2) == pytest.approx(2.0)


def test_weight_defaults_and_zero_weight_floor():
    q = TenantQuotas(weights={"big": 4.0, "typo": 0.0})
    assert q.weight("big") == 4.0
    assert q.weight("unknown") == 1.0
    assert q.weight("typo") == pytest.approx(1e-3)  # never divides by 0


def test_quotas_snapshot_shape():
    q = TenantQuotas(max_waiting_requests=2, weights={"a": 2.0})
    q.note_queue_wait("a", 0.5)
    snap = q.snapshot()
    assert snap == {"a": {"weight": 2.0, "queue_wait_p50_s": 0.5}}


# ---------------------------------------------------------------------------
# mocker: per-tenant quota bounce, fair-share ordering, debug view


async def test_mocker_tenant_quota_bounces_only_the_offender():
    """Storming tenant hits ITS budget and 429s with its own Retry-After;
    a different tenant admits straight through the same engine."""
    eng = MockerEngine(MockerArgs(
        speedup_ratio=100.0,
        tenant_max_waiting_requests=1,
        max_decode_slots=1,  # serialized service: the rest must wait
    ))
    try:
        prompt = list(range(1, 17))  # 4 blocks
        gens = [collect(eng, req(prompt, 8, tenant="storm"))
                for _ in range(6)]
        tasks = [asyncio.ensure_future(g) for g in gens]
        done, rejected = 0, []
        for t in tasks:
            try:
                toks = await t
                assert len(toks) == 8
                done += 1
            except EngineOverloadedError as e:
                rejected.append(e)
        assert done >= 1
        assert rejected, "the storm must exhaust its own tenant budget"
        for e in rejected:
            assert e.tenant == "storm"
            assert e.retry_after_s > 0
        # the OTHER tenant's slice is untouched: admits immediately
        toks = await collect(eng, req(prompt, 4, tenant="calm"))
        assert len(toks) == 4
        assert TENANT.get("dynamo_tenant_rejected_total",
                          "storm") == len(rejected)
        assert TENANT.get("dynamo_tenant_rejected_total", "calm") == 0
        assert TENANT.get("dynamo_tenant_admitted_total", "calm") == 1
    finally:
        await eng.stop()


async def test_mocker_sfq_lets_light_tenant_overtake_the_storm():
    """Service is serialized (pool fits one request); tenant-a enqueues
    a backlog, then tenant-b's single request arrives LAST. SFQ stamps
    b near the virtual clock, so b finishes ahead of a's backlog tail —
    strict FIFO would finish b dead last."""
    eng = MockerEngine(MockerArgs(
        speedup_ratio=30.0,
        max_decode_slots=1,  # one request in service at a time
        tenant_max_waiting_requests=64,
        tenant_weights={"tenant-b": 4.0},
    ))
    try:
        prompt = list(range(1, 17))
        order: list[str] = []

        async def run(tenant):
            await collect(eng, req(prompt, 4, tenant=tenant))
            order.append(tenant)

        tasks = [asyncio.ensure_future(run("tenant-a")) for _ in range(4)]
        await asyncio.sleep(0)  # let every a-request enqueue first
        tasks.append(asyncio.ensure_future(run("tenant-b")))
        await asyncio.gather(*tasks)
        assert order.count("tenant-a") == 4
        # b submitted last but must NOT finish last (FIFO's outcome);
        # its stamp lands near the head, behind at most the in-flight a
        assert order.index("tenant-b") <= 1, order
    finally:
        await eng.stop()


async def test_mocker_tenant_debug_shape_matches_engine_contract():
    eng = MockerEngine(MockerArgs(
        speedup_ratio=100.0, tenant_max_waiting_requests=3,
        tenant_weights={"acme": 2.0},
    ))
    try:
        await collect(eng, req(range(1, 9), 4, tenant="acme"))
        dbg = eng.tenant_debug()
        assert dbg["bounded"] is True
        assert dbg["max_waiting_requests"] == 3
        assert dbg["n_adapters"] == 0
        acme = dbg["tenants"]["acme"]
        assert acme["waiting_requests"] == 0  # drained
        assert acme["weight"] == 2.0
        assert acme["queue_wait_p50_s"] >= 0
        assert acme["metrics"]["dynamo_tenant_admitted_total"] == 1
        # round-trips as JSON (it is a debug HTTP payload)
        json.dumps(dbg)
    finally:
        await eng.stop()


# ---------------------------------------------------------------------------
# wire: the tenant key survives the endpoint error frame


async def test_endpoint_frame_carries_tenant_through_overload():
    from dynamo_tpu.runtime.endpoint import EndpointServer, call_endpoint

    async def handler(payload):
        raise EngineOverloadedError(
            "tenant over quota", retry_after_s=2.5, tenant="acme")
        yield  # pragma: no cover — makes this an async generator

    srv = EndpointServer(handler)
    host, port = await srv.start()
    try:
        with pytest.raises(EngineOverloadedError) as ei:
            async for _ in call_endpoint(host, port, {"x": 1}):
                pass
        assert ei.value.tenant == "acme"
        assert ei.value.retry_after_s == 2.5
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# frontend: variant registration stamps the adapter row + cache salt


def test_register_variant_shares_the_base_chain():
    from dynamo_tpu.frontend.model_manager import (
        ModelChain,
        ModelManager,
        ModelNotFound,
    )

    class _StubPre:
        def preprocess_completion(self, r):
            return PreprocessedRequest(token_ids=[1, 2], model="base")

    engine = object()
    mgr = ModelManager()
    mgr.register(ModelChain(name="base", preprocessor=_StubPre(),
                            engine=engine, backend=None))
    var = mgr.register_variant("base:support-bot", "base", adapter_id=2)
    # ONE engine (one weight load, one tokenizer) behind both names
    assert var.engine is engine
    assert mgr.get("base:support-bot").adapter_id == 2
    assert mgr.get("base").adapter_id == 0

    from dynamo_tpu.protocols.openai import CompletionRequest

    creq = CompletionRequest(model="base:support-bot", prompt="hi")
    pre = var.preprocess(creq)
    assert pre.adapter_id == 2
    # the VARIANT name is the prefix-cache salt: adapter deltas change
    # hidden states, so variants never share cached KV with the base
    assert pre.model == "base:support-bot"
    base_pre = mgr.get("base").preprocess(creq)
    assert base_pre.adapter_id == 0 and base_pre.model == "base"

    with pytest.raises(ValueError):
        mgr.register_variant("bad", "base", adapter_id=0)
    with pytest.raises(ModelNotFound):
        mgr.register_variant("x", "no-such-base", adapter_id=1)


# ---------------------------------------------------------------------------
# model_resolver: aliasing variant names share ONE weight load


def test_aliasing_spellings_resolve_to_one_shared_load(tmp_path):
    from dynamo_tpu.model_resolver import resolve_model, resolver_cache_clear

    resolver_cache_clear()
    d = tmp_path / "model"
    d.mkdir()
    link = tmp_path / "variant-alias"
    os.symlink(d, link)
    try:
        r1 = resolve_model(str(d))
        r2 = resolve_model(str(d) + "/")       # trailing slash
        r3 = resolve_model(str(link))          # symlinked variant dir
        r4 = resolve_model(
            os.path.join(str(tmp_path), ".", "model"))  # dot segment
        # one canonical object — engine caches keyed on it load once
        assert r2 is r1 and r3 is r1 and r4 is r1
        # the first-seen spelling is preserved (existing contract:
        # resolve_model(str(d)).path == str(d))
        assert r1.path == str(d)
    finally:
        resolver_cache_clear()


def test_resolver_cache_clear_isolates_resolutions(tmp_path):
    from dynamo_tpu.model_resolver import resolve_model, resolver_cache_clear

    resolver_cache_clear()
    d = tmp_path / "m"
    d.mkdir()
    r1 = resolve_model(str(d))
    resolver_cache_clear()
    assert resolve_model(str(d)) is not r1
    resolver_cache_clear()


# ---------------------------------------------------------------------------
# engine: adapter 0 is the exact identity; nonzero adapters diverge


def _ecfg(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    base = dict(
        num_pages=128, page_size=16, max_pages_per_seq=16,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


@pytest.mark.asyncio_timeout(300)
async def test_adapter_zero_is_token_identical_and_variants_diverge():
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.tenancy.adapters import random_adapter

    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    plain = TpuEngine(cfg, _ecfg(), params=params,
                      mesh_config=MeshConfig(tp=1))
    banked = TpuEngine(cfg, _ecfg(lora_adapters=4, lora_rank=4),
                       params=params, mesh_config=MeshConfig(tp=1))
    try:
        banked.install_adapter(
            2, random_adapter(cfg, 4, seed=7, scale=0.5))
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, 256, 40).tolist()
        base_toks = await collect(plain, req(prompt, 12))

        # adapter 0 through a BANKED engine: greedy token-identical to
        # an engine with no bank at all (the zero-factor delta is 0.0)
        assert await collect(banked, req(prompt, 12)) == base_toks
        # the installed variant actually changes the stream. The variant
        # model name rides along as the prefix-cache salt — exactly what
        # ModelChain.preprocess stamps — so the variant never reuses the
        # base run's cached KV
        var_toks = await collect(
            banked, req(prompt, 12, adapter_id=2, model="base:v2"))
        assert var_toks != base_toks
        # mixed adapters in ONE batch keep per-stream identity
        mixed = await asyncio.gather(
            collect(banked, req(prompt, 12)),
            collect(banked,
                    req(prompt, 12, adapter_id=2, model="base:v2")),
        )
        assert mixed[0] == base_toks and mixed[1] == var_toks
        # tenant-sliced adapter accounting saw the variant rounds
        assert TENANT.get("dynamo_tenant_adapter_rounds_total",
                          DEFAULT_TENANT) >= 1
        # out-of-range rows are refused at intake, not on device
        with pytest.raises(ValueError, match="out of range"):
            await collect(banked, req(prompt, 4, adapter_id=9))
        with pytest.raises(ValueError):
            await collect(plain, req(prompt, 4, adapter_id=1))
    finally:
        await plain.stop()
        await banked.stop()


# ---------------------------------------------------------------------------
# tools/tenant_stats.py exit contract (like tools/kv_fleet.py's)


async def _run_tool(*args):
    proc = await asyncio.create_subprocess_exec(
        sys.executable, str(REPO_ROOT / "tools" / "tenant_stats.py"),
        *args,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE,
        cwd=str(REPO_ROOT),
    )
    out, err = await proc.communicate()
    return proc.returncode, out.decode(), err.decode()


async def test_tenant_stats_tool_exit_contract():
    from aiohttp.test_utils import TestServer

    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.model_manager import ModelChain

    eng = MockerEngine(MockerArgs(speedup_ratio=100.0,
                                  tenant_max_waiting_requests=4))
    mgr = ModelManager()
    mgr.register(ModelChain(name="mock", preprocessor=None,
                            engine=eng, backend=None))
    svc = HttpService(mgr)
    server = TestServer(svc.app)
    await server.start_server()
    addr = f"127.0.0.1:{server.port}"
    try:
        # 1: reachable but no tenant has been seen yet
        rc, out, _ = await _run_tool("--frontend", addr)
        assert rc == 1, out
        assert json.loads(out)["engines"]["mock"]["tenants"] == {}

        # 0: traffic observed, JSON view on stdout
        await collect(eng, req(range(1, 9), 4, tenant="acme"))
        rc, out, _ = await _run_tool("--frontend", addr)
        assert rc == 0, out
        body = json.loads(out)
        view = body["engines"]["mock"]["tenants"]["acme"]
        assert view["metrics"]["dynamo_tenant_admitted_total"] == 1
        assert "acme" in body["tenants"]

        # 0 with a known --tenant filter; other tenants drop out
        await collect(eng, req(range(1, 9), 4, tenant="other"))
        rc, out, _ = await _run_tool("--frontend", addr,
                                     "--tenant", "acme")
        assert rc == 0
        assert set(json.loads(out)["engines"]["mock"]["tenants"]) == {
            "acme"}

        # 2: unknown tenant, unreachable endpoint, usage error
        rc, _, err = await _run_tool("--frontend", addr,
                                     "--tenant", "ghost")
        assert rc == 2 and "not seen" in err
        rc, _, err = await _run_tool("--frontend", "127.0.0.1:1")
        assert rc == 2 and "cannot reach" in err
        rc, _, _ = await _run_tool()  # missing --frontend
        assert rc == 2
    finally:
        await eng.stop()
        await server.close()
