"""Serve supervisor tests (reference deploy/sdk cli/serving.py circus
arbiter): launch a whole graph from a file, restart crashed workers,
drain gracefully."""
import asyncio
import json
import os
import signal

import aiohttp
import pytest

from dynamo_tpu.launch.serve import Supervisor, load_graph


def write_graph(tmp_path, port_cp, port_http):
    graph = {
        "namespace": "sv",
        "control_plane": {"port": port_cp},
        "frontend": {"http_port": port_http},
        "workers": [
            {"name": "mock", "replicas": 2,
             "args": ["out=mocker", "--model-name", "svm",
                      "--page-size", "4"]},
        ],
    }
    p = tmp_path / "graph.json"
    p.write_text(json.dumps(graph))
    return str(p)


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.asyncio_timeout(300)
async def test_serve_graph_end_to_end(tmp_path):
    port_cp, port_http = free_port(), free_port()
    path = write_graph(tmp_path, port_cp, port_http)
    sup = Supervisor(load_graph(path))
    await sup.start()
    try:
        assert set(sup.status()) == {
            "control-plane", "mock-0", "mock-1", "frontend"
        }

        # the whole graph comes up and serves over HTTP
        url = f"http://127.0.0.1:{port_http}"
        async with aiohttp.ClientSession() as s:
            for _ in range(240):
                try:
                    async with s.get(f"{url}/v1/models") as r:
                        body = await r.json()
                        if [m["id"] for m in body["data"]] == ["svm"]:
                            break
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.5)
            else:
                raise AssertionError(f"graph never served: {sup.status()}")

            async with s.post(f"{url}/v1/chat/completions", json={
                "model": "svm",
                "messages": [{"role": "user", "content": "w1 w2 w3"}],
                "max_tokens": 4,
            }) as r:
                assert r.status == 200
                # mocker tokens may hit synthetic EOS early; service works
                assert 1 <= (await r.json())["usage"]["completion_tokens"] <= 4

            # crash a worker: the supervisor restarts it and service holds
            victim = next(c for c in sup.children if c.name == "mock-0")
            old_pid = victim.proc.pid
            os.kill(old_pid, signal.SIGKILL)
            for _ in range(120):
                if victim.alive() and victim.proc.pid != old_pid:
                    break
                await asyncio.sleep(0.5)
            assert victim.alive() and victim.proc.pid != old_pid
            assert len(victim.restarts) == 1

            async with s.post(f"{url}/v1/chat/completions", json={
                "model": "svm",
                "messages": [{"role": "user", "content": "w4 w5"}],
                "max_tokens": 2,
            }) as r:
                assert r.status == 200
    finally:
        await sup.drain()
    assert all(v != "up" for v in sup.status().values()), sup.status()
