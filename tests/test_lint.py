"""dynlint self-test: every rule fires on a known-bad fixture, the
suppression pragma works, the CLI exit/JSON contract holds, and — the
actual gate — the whole tree lints clean with zero unsuppressed
findings."""
import json
import os
import subprocess
import sys
from pathlib import Path

from dynamo_tpu.lint import all_rules, lint_paths, lint_source

REPO_ROOT = Path(__file__).resolve().parents[1]
DYNLINT = REPO_ROOT / "tools" / "dynlint.py"


def rules_fired(source: str, path: str) -> set:
    return {f.rule for f in lint_source(source, path, root=str(REPO_ROOT))
            if not f.suppressed}


# ---------------------------------------------------------------------------
# one known-bad fixture per rule

def test_dtl001_fires_on_host_effect_in_jitted_fn():
    bad = (
        "import time\n"
        "import jax\n"
        "\n"
        "def _step_impl(x):\n"
        "    return x * time.time()\n"
        "\n"
        "step = jax.jit(_step_impl)\n"
    )
    assert "DTL001" in rules_fired(bad, "dynamo_tpu/models/bad_model.py")


def test_dtl001_ignores_untraced_code():
    ok = (
        "import time\n"
        "\n"
        "def host_side(x):\n"
        "    return x * time.time()\n"
    )
    assert "DTL001" not in rules_fired(ok, "dynamo_tpu/models/ok_model.py")


def test_dtl002_fires_on_blocking_call_in_async_def():
    bad = (
        "import time\n"
        "\n"
        "async def tick():\n"
        "    time.sleep(0.1)\n"
    )
    assert "DTL002" in rules_fired(bad, "dynamo_tpu/runtime/bad_loop.py")


def test_dtl003_fires_on_unguarded_field_access():
    bad = (
        "import threading\n"
        "\n"
        "class TpuEngine:\n"
        "    def __init__(self):\n"
        "        self._wt_lock = threading.Lock()\n"
        "        self._waiting_tokens = {}\n"
        "\n"
        "    def peek(self):\n"
        "        return len(self._waiting_tokens)\n"
    )
    assert "DTL003" in rules_fired(bad, "dynamo_tpu/engine/engine.py")


def test_dtl003_accepts_guarded_access():
    ok = (
        "import threading\n"
        "\n"
        "class TpuEngine:\n"
        "    def __init__(self):\n"
        "        self._wt_lock = threading.Lock()\n"
        "        self._waiting_tokens = {}\n"
        "\n"
        "    def peek(self):\n"
        "        with self._wt_lock:\n"
        "            return len(self._waiting_tokens)\n"
    )
    assert "DTL003" not in rules_fired(ok, "dynamo_tpu/engine/engine.py")


def test_dtl004_fires_on_unaccounted_device_put():
    bad = (
        "import jax\n"
        "\n"
        "class Engine:\n"
        "    def push(self, x):\n"
        "        return jax.device_put(x)\n"
    )
    assert "DTL004" in rules_fired(bad, "dynamo_tpu/engine/bad_engine.py")


def test_dtl004_accepts_accounted_device_put():
    ok = (
        "import jax\n"
        "\n"
        "class Engine:\n"
        "    def push(self, x):\n"
        "        self.dispatch_counts['fetch'] += 1\n"
        "        return jax.device_put(x)\n"
    )
    assert "DTL004" not in rules_fired(ok, "dynamo_tpu/engine/ok_engine.py")


def test_dtl005_fires_on_invalid_family_type():
    bad = (
        "from dynamo_tpu.telemetry.metrics import CounterRegistry\n"
        "\n"
        "FAMILIES = (\n"
        "    ('dynamo_bogus_total', 'kounter', 'bogus things'),\n"
        ")\n"
        "BOGUS = CounterRegistry(FAMILIES, label='bogus')\n"
    )
    assert "DTL005" in rules_fired(bad, "dynamo_tpu/bogus/metrics.py")


def test_dtl006_fires_on_unregistered_wire_exception():
    bad = (
        "class FlakyLinkError(ConnectionError):\n"
        "    pass\n"
    )
    assert "DTL006" in rules_fired(bad, "dynamo_tpu/runtime/bad_errors.py")


def test_dtl006_fires_on_unregistered_nack_kind():
    bad = (
        "def nack(writer):\n"
        "    frame = {'ok': False, 'kind': 'mystery'}\n"
        "    return frame\n"
    )
    assert "DTL006" in rules_fired(bad, "dynamo_tpu/engine/kv_transfer.py")


def test_dtl007_fires_on_silent_broad_except():
    bad = (
        "def f(g):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    assert "DTL007" in rules_fired(bad, "dynamo_tpu/runtime/bad_except.py")


def test_dtl007_accepts_logged_broad_except():
    ok = (
        "import logging\n"
        "log = logging.getLogger(__name__)\n"
        "\n"
        "def f(g):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        log.debug('probe failed', exc_info=True)\n"
    )
    assert "DTL007" not in rules_fired(ok, "dynamo_tpu/runtime/ok_except.py")


# ---------------------------------------------------------------------------
# suppression pragma

BAD_EXCEPT = (
    "def f(g):\n"
    "    try:\n"
    "        g()\n"
    "    except Exception:{pragma}\n"
    "        pass\n"
)


def test_trailing_pragma_suppresses_and_captures_justification():
    src = BAD_EXCEPT.format(
        pragma="  # dynlint: disable=DTL007 — test probe is best-effort")
    fs = [f for f in lint_source(src, "dynamo_tpu/runtime/x.py",
                                 root=str(REPO_ROOT))
          if f.rule == "DTL007"]
    assert len(fs) == 1
    assert fs[0].suppressed
    assert fs[0].justification == "test probe is best-effort"


def test_standalone_pragma_guards_next_line():
    src = (
        "def f(g):\n"
        "    try:\n"
        "        g()\n"
        "    # dynlint: disable=DTL007 — fixture\n"
        "    except Exception:\n"
        "        pass\n"
    )
    fs = [f for f in lint_source(src, "dynamo_tpu/runtime/x.py",
                                 root=str(REPO_ROOT))
          if f.rule == "DTL007"]
    assert len(fs) == 1 and fs[0].suppressed


def test_file_pragma_suppresses_whole_file():
    src = ("# dynlint: disable-file=DTL007 — fixture file\n"
           + BAD_EXCEPT.format(pragma=""))
    fs = [f for f in lint_source(src, "dynamo_tpu/runtime/x.py",
                                 root=str(REPO_ROOT))
          if f.rule == "DTL007"]
    assert len(fs) == 1 and fs[0].suppressed


def test_pragma_only_suppresses_named_rule():
    src = BAD_EXCEPT.format(pragma="  # dynlint: disable=DTL001")
    fs = [f for f in lint_source(src, "dynamo_tpu/runtime/x.py",
                                 root=str(REPO_ROOT))
          if f.rule == "DTL007"]
    assert len(fs) == 1 and not fs[0].suppressed


# ---------------------------------------------------------------------------
# the gate: the tree lints clean

def test_tree_has_zero_unsuppressed_findings():
    findings = lint_paths(["dynamo_tpu", "tools"], root=str(REPO_ROOT))
    active = [f for f in findings if not f.suppressed]
    assert not active, "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in active)


def test_every_suppression_carries_a_justification():
    findings = lint_paths(["dynamo_tpu", "tools"], root=str(REPO_ROOT))
    bare = [f for f in findings if f.suppressed and not f.justification]
    assert not bare, "\n".join(
        f"{f.path}:{f.line}: {f.rule} suppressed without justification"
        for f in bare)


def test_all_seven_rules_are_registered():
    assert {r.ID for r in all_rules()} == {
        "DTL001", "DTL002", "DTL003", "DTL004", "DTL005", "DTL006",
        "DTL007",
    }


# ---------------------------------------------------------------------------
# CLI exit-status + JSON contract

def run_cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, str(DYNLINT), *args],
        capture_output=True, text=True, cwd=cwd or str(REPO_ROOT),
    )


def test_cli_clean_tree_exits_zero_with_json():
    p = run_cli("--format", "json", "dynamo_tpu", "tools")
    assert p.returncode == 0, p.stdout + p.stderr
    data = json.loads(p.stdout)
    assert data["exit_code"] == 0
    assert data["counts"]["active"] == 0
    # suppressed findings still appear in JSON, with justifications
    for f in data["findings"]:
        assert f["suppressed"] and f.get("justification")


def test_cli_findings_exit_one_with_locations(tmp_path):
    (tmp_path / "bad.py").write_text(
        "def f(g):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    p = run_cli("--root", str(tmp_path), "--format", "json", "bad.py")
    assert p.returncode == 1, p.stdout + p.stderr
    data = json.loads(p.stdout)
    assert data["exit_code"] == 1
    assert data["counts"]["by_rule"] == {"DTL007": 1}
    f = data["findings"][0]
    assert (f["rule"], f["path"], f["line"]) == ("DTL007", "bad.py", 4)


def test_cli_usage_errors_exit_two(tmp_path):
    assert run_cli("--rules", "DTL999", "dynamo_tpu").returncode == 2
    assert run_cli("no/such/path.py").returncode == 2


def test_cli_syntax_error_is_a_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    p = run_cli("--root", str(tmp_path), "--format", "json", "broken.py")
    assert p.returncode == 1
    data = json.loads(p.stdout)
    assert any(f["rule"] == "DTL000" for f in data["findings"])


def test_cli_rules_filter_restricts_output(tmp_path):
    (tmp_path / "bad.py").write_text(
        "import time\n"
        "async def tick():\n"
        "    time.sleep(0.1)\n"
        "    try:\n"
        "        tick\n"
        "    except Exception:\n"
        "        pass\n"
    )
    rel = os.path.join("runtime", "bad.py")
    (tmp_path / "runtime").mkdir()
    (tmp_path / rel).write_text((tmp_path / "bad.py").read_text())
    p = run_cli("--root", str(tmp_path), "--format", "json",
                "--rules", "DTL002", rel)
    data = json.loads(p.stdout)
    assert {f["rule"] for f in data["findings"]} == {"DTL002"}
