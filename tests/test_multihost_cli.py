"""Cross-host engine through the REAL CLI launcher (the config-4 serving
path end to end): two `dynamo-tpu run` processes — rank 0 in=text serving
a prompt over the global tp=4 mesh, rank 1 as the replay follower — with
the store, barrier rendezvous, jax.distributed bootstrap, command stream,
and leader-liveness teardown all exercised by the launcher itself
(launch/run.py multi_host_bootstrap + _crosshost_prologue).
"""
import asyncio
import os
import socket
import subprocess
import sys

import pytest

from dynamo_tpu.runtime.store import serve_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.asyncio_timeout(420)
async def test_cli_crosshost_text_serving():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    store_port = server.sockets[0].getsockname()[1]
    coord = _free_port()

    def spawn(rank: int, io: list[str]):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PYTHONPATH"] = REPO
        return subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.cli", "run", *io,
             "out=tpu", "--model-config", "tiny_wide",
             "--tensor-parallel-size", "4",
             "--num-nodes", "2", "--node-rank", str(rank),
             "--leader-addr", f"127.0.0.1:{coord}",
             "--control-plane", f"127.0.0.1:{store_port}",
             "--page-size", "16", "--num-pages", "32",
             "--max-decode-slots", "2", "--cache-dtype", "float32",
             "--prompt", "w1 w2 w3", "--max-tokens", "6"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )

    leader = spawn(0, ["in=text"])
    follower = spawn(1, ["in=endpoint"])
    try:
        l_out, l_err = await asyncio.to_thread(leader.communicate, None, 300)
    except subprocess.TimeoutExpired:
        leader.kill()
        follower.kill()
        raise

    assert leader.returncode == 0, (
        f"leader failed:\nstdout:{l_out[-1500:]}\nstderr:{l_err[-2500:]}"
    )
    assert "multi-host engine up: node 0/2" in (l_out + l_err)
    assert "4 global devices" in (l_out + l_err)
    # in=text prints the completion; tiny random weights emit test-vocab
    # words — just require a non-empty generation line
    assert any(line.strip() for line in l_out.splitlines()
               if not line.startswith(("multi-host", "cross-host")))

    # leader exit -> liveness key expiry -> follower exits on its own
    try:
        f_out, f_err = await asyncio.to_thread(follower.communicate, None, 90)
    except subprocess.TimeoutExpired:
        follower.kill()
        raise AssertionError(
            "follower did not exit after leader death (liveness teardown)"
        )
    finally:
        server.close()
    assert follower.returncode == 0, f_err[-2000:]
