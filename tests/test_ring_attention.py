"""Ring-attention sequence parallelism tests (SURVEY §2.5 SP row — absent
in the reference; our TPU-native long-context path). Run on the virtual
8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.ring_attention import ring_attention, sp_shard


def sp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def full_causal(q, k, v):
    """Single-device reference."""
    H = q.shape[1]
    qt = q.transpose(1, 0, 2)
    kt = k.transpose(1, 0, 2)
    vt = v.transpose(1, 0, 2)
    s = jnp.einsum("htd,hsd->hts", qt, kt,
                   preferred_element_type=jnp.float32)
    s = s / np.sqrt(q.shape[-1])
    T = q.shape[0]
    mask = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
    s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hts,hsd->thd", p, vt.astype(p.dtype)).astype(q.dtype)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_full_attention(sp):
    rng = np.random.default_rng(0)
    T, H, D = 64, 4, 16
    q = jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
    ref = full_causal(q, k, v)

    mesh = sp_mesh(sp)
    out = ring_attention(
        sp_shard(q, mesh), sp_shard(k, mesh), sp_shard(v, mesh), mesh
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = sp_mesh(4)
    x = jnp.zeros((30, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(x, x, x, mesh)


def test_sp_prefill_matches_single_device():
    """Whole-transformer SP prefill: logits equal the paged single-device
    prefill for the same prompt + params."""
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    prompt = list(range(1, 41))  # 40 valid tokens
    T = 64                        # padded, divisible by sp=8
    toks = np.zeros(T, np.int32)
    toks[: len(prompt)] = prompt

    # reference: single-device contiguous-ctx prefill
    ctx = llama.init_ctx(cfg, 1, T, jnp.float32)
    _, ref_logits = llama.prefill(
        cfg, params, ctx, jnp.asarray(toks), jnp.int32(0),
        jnp.int32(0), jnp.int32(len(prompt)),
    )

    mesh = sp_mesh(8)
    kv, logits = llama.sp_prefill(
        cfg, params, sp_shard(jnp.asarray(toks), mesh),
        jnp.int32(len(prompt)), mesh,
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    # KV layout: [L, kvh, T, hd], valid positions match the paged pool
    assert kv["k"].shape == (cfg.num_layers, cfg.num_kv_heads, T,
                             cfg.head_dim)
