"""Engine tests: page allocator semantics + continuous-batching engine
correctness on the tiny CPU model.

The keystone equivalence test runs the full async engine greedily and checks
its tokens equal a hand-driven prefill/decode loop on the raw model — any
scheduler off-by-one (ctx lengths, page growth, commit timing) breaks it.
"""
import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.cache import PageAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_router.protocols import KvEventKind
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.tokens import compute_block_hashes

PS = 16


# ---------------------------------------------------------------------------
# PageAllocator

def test_allocator_alloc_free_reuse():
    a = PageAllocator(num_pages=8, page_size=PS)
    p = a.allocate(3)
    assert p is not None and len(set(p)) == 3 and 0 not in p
    assert a.active_pages == 3
    a.free(p)
    assert a.active_pages == 0
    assert a.allocate(7) is not None
    assert a.allocate(1) is None  # exhausted (7 real pages)


def test_allocator_prefix_reuse_and_eviction():
    events = []
    a = PageAllocator(num_pages=6, page_size=PS, on_event=events.append)
    hashes = compute_block_hashes(list(range(PS * 3)), PS)
    pages = a.allocate(3)
    parent = 0
    for pg, h in zip(pages, hashes):
        assert a.commit(pg, h, parent)
        parent = h
    assert [e.kind for e in events] == [KvEventKind.STORED] * 3
    a.free(pages)
    # all three parked in LRU, still matchable
    m = a.match_prefix(hashes)
    assert m == pages
    a.free(m)
    # allocation pressure evicts LRU-oldest first
    p2 = a.allocate(5)
    assert p2 is not None
    removed = [e for e in events if e.kind == KvEventKind.REMOVED]
    assert len(removed) == 3
    assert removed[0].removed_hashes == [hashes[0]]
    assert a.match_prefix(hashes) == []


def test_allocator_refcounted_sharing():
    a = PageAllocator(num_pages=6, page_size=PS)
    hashes = compute_block_hashes(list(range(PS * 2)), PS)
    pages = a.allocate(2)
    a.commit(pages[0], hashes[0], 0)
    a.commit(pages[1], hashes[1], hashes[0])
    m1 = a.match_prefix(hashes)   # second ref
    a.free(pages)                 # first user done; still referenced
    assert a.available_pages == 3
    a.free(m1)
    assert a.available_pages == 5  # parked in LRU, available via eviction


# ---------------------------------------------------------------------------
# Engine

@pytest.fixture(scope="module")
def engine_setup():
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64,
        page_size=PS,
        max_pages_per_seq=8,
        max_decode_slots=4,
        prefill_buckets=(32, 64),
        cache_dtype="float32",
        worker_id="w0",
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def make_engine(engine_setup, **kw):
    cfg, ecfg, params = engine_setup
    from dataclasses import replace

    if kw:
        ecfg = replace(ecfg, **kw)
    from dynamo_tpu.parallel.mesh import MeshConfig

    return TpuEngine(
        cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1)
    )


async def collect(engine, req):
    toks, finish = [], None
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
        if out.finish_reason:
            finish = out.finish_reason
    return toks, finish


def manual_greedy(cfg, params, ecfg, prompt, n_new):
    """Hand-driven reference loop on the raw model (contiguous ctx)."""
    ctx = llama.init_ctx(cfg, 1, ecfg.max_context, jnp.float32)
    pad = ((len(prompt) + 31) // 32) * 32
    toks = np.zeros(pad, np.int32)
    toks[: len(prompt)] = prompt
    ctx, logits = llama.prefill(
        cfg, params, ctx, jnp.asarray(toks), jnp.int32(0),
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    out = [int(np.argmax(np.asarray(logits)))]
    seq_len = len(prompt)
    ring = llama.init_ring(cfg, 1, 1, dtype=jnp.float32)  # 1-step rounds
    for _ in range(n_new - 1):
        seq_len += 1
        ring_base = jnp.asarray([seq_len - 1], jnp.int32)
        ring, lg = llama.decode_step(
            cfg, params, ctx, ring,
            jnp.asarray([out[-1]], jnp.int32),
            jnp.asarray([seq_len], jnp.int32),
            ring_base, jnp.int32(0),
        )
        ctx = llama.flush_ctx(
            ctx, ring, jnp.asarray([0], jnp.int32), ring_base,
            jnp.asarray([1], jnp.int32),
        )
        out.append(int(np.argmax(np.asarray(lg)[0])))
    return out


async def test_engine_matches_manual_loop(engine_setup):
    cfg, ecfg, params = engine_setup
    eng = make_engine(engine_setup)
    prompt = list(range(1, 25))  # 24 tokens: crosses a page boundary quickly
    n_new = 20
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )
    toks, finish = await collect(eng, req)
    ref = manual_greedy(cfg, params, ecfg, prompt, n_new)
    assert toks == ref
    assert finish is not None and finish.value == "length"
    await eng.stop()


async def test_engine_concurrent_requests_deterministic(engine_setup):
    eng = make_engine(engine_setup)
    prompts = [list(range(1 + i, 20 + i)) for i in range(6)]  # > slot count

    async def one(p):
        req = PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=10, ignore_eos=True),
        )
        return (await collect(eng, req))[0]

    batch = await asyncio.gather(*[one(p) for p in prompts])
    solo = [await one(p) for p in prompts]
    assert batch == solo  # batching must not change greedy results
    await eng.stop()


async def test_engine_prefix_cache_hit(engine_setup):
    eng = make_engine(engine_setup)
    prompt = list(range(1, 40))  # 39 tokens = 2 complete blocks + tail
    req = lambda: PreprocessedRequest(  # noqa: E731
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
    )
    t1, _ = await collect(eng, req())
    hits_before = eng.allocator.hit_blocks
    t2, _ = await collect(eng, req())
    assert t1 == t2
    assert eng.allocator.hit_blocks > hits_before  # 2 blocks re-matched
    await eng.stop()


async def test_engine_eos_stop(engine_setup):
    eng = make_engine(engine_setup)
    prompt = list(range(1, 20))
    base = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
    )
    toks, _ = await collect(eng, base)
    eos = toks[2]  # pretend the 3rd generated token is EOS
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=8, stop_token_ids=[eos]),
    )
    toks2, finish = await collect(eng, req)
    assert toks2 == toks[:2]
    assert finish.value == "eos"
    await eng.stop()


async def test_engine_preemption_under_pressure(engine_setup):
    # 15 real pages, 4 slots x up to 8 pages each -> guaranteed pressure
    eng = make_engine(engine_setup, num_pages=16)
    prompts = [list(range(1 + 7 * i, 30 + 7 * i)) for i in range(4)]

    async def one(p):
        req = PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=40, ignore_eos=True),
        )
        return (await collect(eng, req))[0]

    outs = await asyncio.gather(*[one(p) for p in prompts])
    assert all(len(o) == 40 for o in outs)
    # preemption must preserve greedy determinism
    solo = await one(prompts[0])
    assert outs[0] == solo
    await eng.stop()


async def test_engine_sampling_seeded(engine_setup):
    eng = make_engine(engine_setup)
    req = lambda seed: PreprocessedRequest(  # noqa: E731
        token_ids=list(range(1, 20)),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.8, top_k=20, seed=seed),
    )
    a, _ = await collect(eng, req(7))
    b, _ = await collect(eng, req(7))
    c, _ = await collect(eng, req(8))
    assert a == b
    assert len(a) == 8
    assert a != c or True  # different seed usually differs; no hard guarantee
    await eng.stop()


async def test_engine_unseeded_sampling_differs(engine_setup):
    """Two identical unseeded prompts must not produce identical streams
    (advisor r1/r2: slot-derived keys made them deterministic)."""
    eng = make_engine(engine_setup)
    req = lambda: PreprocessedRequest(  # noqa: E731
        token_ids=list(range(1, 20)),
        stop_conditions=StopConditions(max_tokens=16, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=1.5, top_k=50),
    )
    # run sequentially so both land on the same freed slot
    outs = [await collect(eng, req()) for _ in range(4)]
    streams = [o[0] for o in outs]
    assert all(len(s) == 16 for s in streams)
    assert len({tuple(s) for s in streams}) > 1
    await eng.stop()


async def test_engine_chunked_prefill_long_prompt(engine_setup):
    """Prompts longer than the largest prefill bucket run as page-aligned
    continuation chunks; logits must match the short-bucket path exactly."""
    cfg, ecfg, params = engine_setup
    eng = make_engine(engine_setup)  # buckets (32, 64); prompt 100 > 64
    prompt = list((np.arange(100) % 250) + 1)
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
    )
    toks, finish = await collect(eng, req)
    ref = manual_greedy(cfg, params, ecfg, prompt, 8)
    assert toks == ref
    await eng.stop()


async def test_prefill_interleaves_with_decode(engine_setup):
    """VERDICT r2 weak #4: a long prompt must NOT stall in-flight decodes
    for its whole prefill — chunks interleave with decode rounds, so the
    running request keeps producing tokens while the long prompt admits."""
    eng = make_engine(engine_setup, prefill_chunks_per_round=1,
                      num_pages=128, max_pages_per_seq=16)
    # A: long-running decode
    req_a = PreprocessedRequest(
        token_ids=list(range(1, 20)),
        stop_conditions=StopConditions(max_tokens=200, ignore_eos=True),
    )
    a_tokens = []
    a_stream = eng.generate(req_a)

    async def pump_a():
        async for out in a_stream:
            a_tokens.extend(out.token_ids)

    task_a = asyncio.create_task(pump_a())
    while len(a_tokens) < 5:  # A is decoding
        await asyncio.sleep(0.01)

    # B: prompt spanning MANY chunks (buckets max 64 -> 3 chunks for 190)
    a_before = len(a_tokens)
    req_b = PreprocessedRequest(
        token_ids=list((np.arange(190) % 250) + 1),
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
    )
    b_first = None
    b_tokens = []
    async for out in eng.generate(req_b):
        if b_first is None and out.token_ids:
            b_first = len(a_tokens)  # A's progress at B's first token
        b_tokens.extend(out.token_ids)
    assert len(b_tokens) == 4
    # A made progress DURING B's multi-chunk prefill window
    assert b_first is not None and b_first > a_before

    task_a.cancel()
    try:
        await task_a
    except asyncio.CancelledError:
        pass
    await eng.stop()


async def test_engine_batched_prefill_groups(engine_setup):
    """Concurrent arrivals must take the batched [K, T] prefill program
    (engine.batch_prefills > 0) and still match solo greedy results —
    including a second wave whose shared prefix makes them q_start>0
    continuation chunks (ctx_span > 0 grouping)."""
    eng = make_engine(engine_setup, prefill_chunks_per_round=8)
    shared = list(range(1, 33))  # 2 complete blocks of shared prefix

    def req(tail):
        return PreprocessedRequest(
            token_ids=shared + [100 + tail, 101 + tail, 102 + tail],
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        )

    # wave 1: fresh concurrent prefills -> one fresh batched dispatch
    wave1 = await asyncio.gather(
        *[collect(eng, req(i)) for i in range(4)]
    )
    assert eng.batch_prefills >= 1
    # wave 2: same prompts again -> prefix hits -> continuation chunks
    # (q_start > 0) batch with ctx_span > 0
    before = eng.batch_prefills
    wave2 = await asyncio.gather(
        *[collect(eng, req(i)) for i in range(4)]
    )
    assert eng.batch_prefills > before
    assert [t for t, _ in wave2] == [t for t, _ in wave1]
    # solo (serial) runs must agree with the batched results
    solo = [await collect(eng, req(i)) for i in range(4)]
    assert [t for t, _ in solo] == [t for t, _ in wave1]
    await eng.stop()


async def test_engine_int8_quantized_serving(engine_setup):
    """w8a16 int8 weights (models/llama.py _mm) serve end-to-end through
    the engine: same prompt twice is deterministic, and greedy tokens
    match a dense engine built from the SAME dense weights quantized —
    int8 per-channel error is far below greedy argmax margins on the tiny
    model (validated at module level in test_llama_model)."""
    cfg, ecfg, params = engine_setup
    from dataclasses import replace as _rep
    from dynamo_tpu.parallel.mesh import MeshConfig

    qcfg = _rep(cfg, quant="int8")
    qparams = llama.quantize_params(params)
    eng = TpuEngine(qcfg, ecfg, params=qparams, mesh_config=MeshConfig(tp=1))
    req = lambda: PreprocessedRequest(  # noqa: E731
        token_ids=list(range(1, 30)),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
    )
    t1, fin = await collect(eng, req())
    t2, _ = await collect(eng, req())
    assert t1 == t2 and len(t1) == 8
    assert fin is not None
    await eng.stop()
