"""GGUF reader tests (reference gguf/content.rs + gguf_tokenizer.rs:587):
a synthetic GGUF v3 file written by the test is read back — metadata,
tensor descriptors, ModelConfig extraction, and the SPM-unigram
tokenizer's encode/decode round trip."""
import struct

import pytest

from dynamo_tpu.gguf import GgufTokenizer, config_from_gguf, read_gguf

_T_U32, _T_F32, _T_BOOL, _T_STRING, _T_ARRAY = 4, 6, 7, 8, 9


def _s(x: str) -> bytes:
    b = x.encode()
    return struct.pack("<Q", len(b)) + b


def _kv(key: str, vtype: int, payload: bytes) -> bytes:
    return _s(key) + struct.pack("<I", vtype) + payload


def _arr(etype: int, items: list[bytes]) -> bytes:
    return struct.pack("<IQ", etype, len(items)) + b"".join(items)


def write_gguf(path, metadata_blobs: list[bytes], tensors=()):
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<IQQ", 3, len(tensors), len(metadata_blobs)))
        for blob in metadata_blobs:
            f.write(blob)
        for name, dims, dtype, off in tensors:
            f.write(_s(name))
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", dtype, off))


VOCAB = ["<unk>", "<s>", "</s>"]
VOCAB += [f"<0x{i:02X}>" for i in range(256)]
PIECES = ["▁hello", "▁world", "▁he", "llo", "▁wor", "ld", "▁", "h", "e",
          "l", "o", "w", "r", "d", "▁hi"]
VOCAB += PIECES
SCORES = [0.0] * 259 + [-1.0, -1.0, -3.0, -3.0, -3.0, -3.0, -5.0, -6.0,
                        -6.0, -6.0, -6.0, -6.0, -6.0, -6.0, -1.5]


def _tok_metadata() -> list[bytes]:
    return [
        _kv("general.architecture", _T_STRING, _s("llama")),
        _kv("llama.embedding_length", _T_U32, struct.pack("<I", 64)),
        _kv("llama.block_count", _T_U32, struct.pack("<I", 4)),
        _kv("llama.attention.head_count", _T_U32, struct.pack("<I", 4)),
        _kv("llama.attention.head_count_kv", _T_U32, struct.pack("<I", 2)),
        _kv("llama.feed_forward_length", _T_U32, struct.pack("<I", 128)),
        _kv("llama.context_length", _T_U32, struct.pack("<I", 512)),
        _kv("llama.rope.freq_base", _T_F32, struct.pack("<f", 10000.0)),
        _kv("llama.attention.layer_norm_rms_epsilon", _T_F32,
            struct.pack("<f", 1e-5)),
        _kv("tokenizer.ggml.model", _T_STRING, _s("llama")),
        _kv("tokenizer.ggml.tokens", _T_ARRAY,
            _arr(_T_STRING, [_s(t) for t in VOCAB])),
        _kv("tokenizer.ggml.scores", _T_ARRAY,
            _arr(_T_F32, [struct.pack("<f", s) for s in SCORES])),
        _kv("tokenizer.ggml.bos_token_id", _T_U32, struct.pack("<I", 1)),
        _kv("tokenizer.ggml.eos_token_id", _T_U32, struct.pack("<I", 2)),
        _kv("tokenizer.ggml.add_bos_token", _T_BOOL, b"\x01"),
    ]


def test_read_gguf_roundtrip(tmp_path):
    path = tmp_path / "m.gguf"
    write_gguf(path, _tok_metadata(),
               tensors=[("token_embd.weight", [64, len(VOCAB)], 0, 0),
                        ("blk.0.attn_q.weight", [64, 64], 0, 4096)])
    md, tensors = read_gguf(str(path))
    assert md["general.architecture"] == "llama"
    assert md["llama.block_count"] == 4
    assert len(md["tokenizer.ggml.tokens"]) == len(VOCAB)
    assert [t["name"] for t in tensors] == [
        "token_embd.weight", "blk.0.attn_q.weight"
    ]
    assert tensors[1]["offset"] == 4096

    cfg = config_from_gguf(md)
    assert cfg.num_layers == 4
    assert cfg.num_kv_heads == 2
    assert cfg.vocab_size == len(VOCAB)
    assert cfg.head_dim == 16


def test_gguf_rejects_non_gguf(tmp_path):
    p = tmp_path / "x.bin"
    p.write_bytes(b"NOTG" + b"\x00" * 64)
    with pytest.raises(ValueError, match="not a GGUF"):
        read_gguf(str(p))


def test_spm_tokenizer_encode_decode(tmp_path):
    path = tmp_path / "m.gguf"
    write_gguf(path, _tok_metadata())
    md, _ = read_gguf(str(path))
    tok = GgufTokenizer.from_metadata(md)

    ids = tok.encode("hello world")
    assert ids[0] == 1  # bos
    # unigram Viterbi picks the highest-scoring pieces
    assert [tok.tokens[i] for i in ids[1:]] == ["▁hello", "▁world"]
    assert tok.decode(ids) == "hello world"

    # piece preference follows scores: "hi" is a whole piece
    ids2 = tok.encode("hi")
    assert [tok.tokens[i] for i in ids2[1:]] == ["▁hi"]

    # byte fallback covers characters outside the vocab, losslessly
    ids3 = tok.encode("héllo")
    assert tok.decode(ids3) == "héllo"

    assert tok.stop_token_ids == [2]


def test_bpe_gguf_dispatch(tmp_path):
    """gpt2-model GGUFs now dispatch to the byte-level BPE tokenizer
    (reference gguf_tokenizer.rs:111,222 handles them; round-4 rejected
    them)."""
    from dynamo_tpu.gguf import GgufBpeTokenizer, gguf_tokenizer

    path = tmp_path / "m.gguf"
    blobs = _tok_metadata()
    blobs[9] = _kv("tokenizer.ggml.model", _T_STRING, _s("gpt2"))
    blobs.append(_kv("tokenizer.ggml.merges", _T_ARRAY, _arr(_T_STRING, [])))
    write_gguf(path, blobs)
    md, _ = read_gguf(str(path))
    assert isinstance(gguf_tokenizer(md), GgufBpeTokenizer)
    with pytest.raises(ValueError, match="not supported"):
        GgufTokenizer.from_metadata(md)


GOLDEN_TEXTS = [
    "Hello world",
    "hello, world!  How's it going?",
    "The quick brown fox jumps over the lazy dog.",
    "  leading spaces and   runs",
    "trailing space ",
    "numbers 123 and 456789 mixed2with words",
    "punct!!! ... --- (mixed) [brackets] {braces}",
    "CamelCase and UPPER and lower",
    "unicode: caf\u00e9 na\u00efve \u00fcber stra\u00dfe",
    "emoji \U0001f600 ok",
    "don't we'll they've I'm you're he'd it's",
    "tabs\tand\nnewlines\r\nmixed \n\n double",
    "a",
    " ",
    "",
    "'quoted' \"double\" `tick`",
]


def test_bpe_tokenizer_matches_hf_bytelevel_golden(tmp_path):
    """Golden parity: the same vocab+merges loaded into HF `tokenizers`
    ByteLevelBPE (the library the reference converts GGUF vocabs INTO,
    gguf_tokenizer.rs:222) and into GgufBpeTokenizer must encode
    identically — pretokenizer scanner, byte mapping, and merge order all
    checked at once."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import ByteLevelBPETokenizer

    from dynamo_tpu.gguf import GgufBpeTokenizer

    ref = ByteLevelBPETokenizer()
    corpus = [
        "hello world how are you doing today",
        "the quick brown fox jumps over the lazy dog",
        "numbers 123 456 789 and punctuation !!! ... ??",
        "don't stop believing, hold on to that feeling",
        "some CamelCase and UPPERCASE and lowercase words",
        "caf\u00e9 na\u00efve \u00fcber stra\u00dfe unicode text",
    ] * 50
    ref.train_from_iterator(corpus, vocab_size=600, min_frequency=1)
    vocab = ref.get_vocab()
    tokens = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    # extract merge list in rank order from the trained model
    import json

    model_json = json.loads(ref.to_str())
    merges = [
        m if isinstance(m, str) else " ".join(m)
        for m in model_json["model"]["merges"]
    ]
    mine = GgufBpeTokenizer(tokens, merges, add_bos=False)
    for text in GOLDEN_TEXTS:
        exp = ref.encode(text).ids
        got = mine.encode(text)
        assert got == exp, (text, [tokens[i] for i in got],
                            [tokens[i] for i in exp])
        assert mine.decode(got) == text


def test_bpe_llama3_pretokenizer_rules():
    """The llama-bpe scanner's divergences from GPT-2: digit triples,
    case-insensitive contractions, punctuation absorbing newlines."""
    from dynamo_tpu.gguf import llama3_pretokenize

    assert llama3_pretokenize("1234567") == ["123", "456", "7"]
    assert llama3_pretokenize("WE'LL go") == ["WE", "'LL", " go"]
    assert llama3_pretokenize("end.\n\nNew") == ["end", ".\n\n", "New"]
    assert llama3_pretokenize("hello world") == ["hello", " world"]
    assert llama3_pretokenize("  indent") == [" ", " indent"]


def test_bpe_special_token_splitting():
    """Control tokens (token_type 3) are matched verbatim and round-trip
    — chat-template markup must not be split by the pretokenizer."""
    from dynamo_tpu.gguf import GgufBpeTokenizer

    base = [chr(c) for c in range(33, 127)]
    tokens = base + ["<|eot_id|>", "<|start_header_id|>"]
    types = [1] * len(base) + [3, 3]
    tok = GgufBpeTokenizer(tokens, [], token_types=types, add_bos=False)
    ids = tok.encode("<|start_header_id|>hi<|eot_id|>")
    assert ids[0] == tokens.index("<|start_header_id|>")
    assert ids[-1] == tokens.index("<|eot_id|>")
    assert tok.decode(ids, skip_special_tokens=False) == "<|start_header_id|>hi<|eot_id|>"
    assert tokens.index("<|eot_id|>") in tok.stop_token_ids


# ---------------------------------------------------------------------------
# Tensor dequantization + weight loading


def _pack_f16(x):
    import numpy as np

    return np.asarray(x, "<f2").tobytes()


def test_dequantize_q8_0():
    """Q8_0 block layout straight from the spec: f16 scale + 32 int8."""
    import numpy as np

    from dynamo_tpu.gguf import GGML_Q8_0, dequantize_tensor

    q = np.arange(-16, 16, dtype=np.int8)
    data = _pack_f16([0.5]) + q.tobytes() + _pack_f16([2.0]) + q.tobytes()
    x = dequantize_tensor(GGML_Q8_0, data, 64)
    np.testing.assert_allclose(x[:32], q * 0.5)
    np.testing.assert_allclose(x[32:], q * 2.0)


def test_dequantize_q4_0_and_q4_1():
    """Q4 nibble order: byte j carries elems j (low) and j+16 (high)."""
    import numpy as np

    from dynamo_tpu.gguf import GGML_Q4_0, GGML_Q4_1, dequantize_tensor

    nibbles = np.arange(16, dtype=np.uint8)          # elem j = j
    qs = (nibbles | (15 - nibbles) << 4).tobytes()   # elem j+16 = 15-j
    x = dequantize_tensor(GGML_Q4_0, _pack_f16([1.5]) + qs, 32)
    np.testing.assert_allclose(x[:16], (nibbles - 8.0) * 1.5)
    np.testing.assert_allclose(x[16:], ((15 - nibbles) - 8.0) * 1.5)
    x1 = dequantize_tensor(
        GGML_Q4_1, _pack_f16([2.0]) + _pack_f16([-3.0]) + qs, 32)
    np.testing.assert_allclose(x1[:16], nibbles * 2.0 - 3.0)


def test_dequantize_q5_0():
    """Q5: the 5th bit of elem j comes from bit j of the u32 qh."""
    import numpy as np
    import struct as _st

    from dynamo_tpu.gguf import GGML_Q5_0, dequantize_tensor

    vals = np.arange(32, dtype=np.uint8)  # 5-bit values 0..31
    qs = bytes((vals[j] & 0xF) | ((vals[j + 16] & 0xF) << 4)
               for j in range(16))
    qh = 0
    for j in range(32):
        qh |= ((int(vals[j]) >> 4) & 1) << j
    data = _pack_f16([1.0]) + _st.pack("<I", qh) + qs
    x = dequantize_tensor(GGML_Q5_0, data, 32)
    np.testing.assert_allclose(x, vals.astype(np.float32) - 16.0)


def test_dequantize_kquant_rejected():
    import pytest as _pytest

    from dynamo_tpu.gguf import dequantize_tensor

    with _pytest.raises(ValueError, match="Q4_K"):
        dequantize_tensor(12, b"", 256)


def _write_gguf_with_data(path, metadata_blobs, named_arrays):
    """GGUF v3 writer incl. F32 tensor data (aligned data section)."""
    import numpy as np

    descs, payload = [], bytearray()
    for name, arr in named_arrays:
        a = np.asarray(arr, "<f4")
        descs.append((name, list(reversed(a.shape)), 0, len(payload)))
        payload.extend(a.tobytes())
        while len(payload) % 32:
            payload.append(0)
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<IQQ", 3, len(descs), len(metadata_blobs)))
        for blob in metadata_blobs:
            f.write(blob)
        for name, dims, dtype, off in descs:
            f.write(_s(name))
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", dtype, off))
        while f.tell() % 32:
            f.write(b"\x00")
        f.write(payload)


def _gguf_permute(w, n_head):
    """The HF->GGUF q/k row permutation (llama.cpp convert script) the
    loader must invert."""
    import numpy as np

    out_dim = w.shape[0]
    return (w.reshape(n_head, 2, out_dim // n_head // 2, *w.shape[1:])
             .swapaxes(1, 2)
             .reshape(w.shape))


def test_load_gguf_params_roundtrip(tmp_path):
    """A tiny model's HF-layout weights written into a GGUF (with the
    llama.cpp q/k permutation applied, as real conversions do) load back
    EQUAL to the originals — name mapping, dim reversal, transposes, and
    the rope unpermute all verified at once. The loaded params then run a
    prefill to prove they're serving-shaped."""
    import numpy as np

    import jax.numpy as jnp

    from dynamo_tpu.gguf import load_gguf_params
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny(dtype="float32", tie_word_embeddings=False)
    rng = np.random.RandomState(0)
    H, I, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_layers)
    hf = {
        "model.embed_tokens.weight": rng.randn(V, H).astype(np.float32),
        "model.norm.weight": rng.randn(H).astype(np.float32),
        "lm_head.weight": rng.randn(V, H).astype(np.float32),
    }
    for l in range(L):
        p = f"model.layers.{l}."
        hf[p + "self_attn.q_proj.weight"] = rng.randn(cfg.q_dim, H).astype(np.float32)
        hf[p + "self_attn.k_proj.weight"] = rng.randn(cfg.kv_dim, H).astype(np.float32)
        hf[p + "self_attn.v_proj.weight"] = rng.randn(cfg.kv_dim, H).astype(np.float32)
        hf[p + "self_attn.o_proj.weight"] = rng.randn(H, cfg.q_dim).astype(np.float32)
        hf[p + "mlp.gate_proj.weight"] = rng.randn(I, H).astype(np.float32)
        hf[p + "mlp.up_proj.weight"] = rng.randn(I, H).astype(np.float32)
        hf[p + "mlp.down_proj.weight"] = rng.randn(H, I).astype(np.float32)
        hf[p + "input_layernorm.weight"] = rng.randn(H).astype(np.float32)
        hf[p + "post_attention_layernorm.weight"] = rng.randn(H).astype(np.float32)

    arrays = [
        ("token_embd.weight", hf["model.embed_tokens.weight"]),
        ("output_norm.weight", hf["model.norm.weight"]),
        ("output.weight", hf["lm_head.weight"]),
    ]
    for l in range(L):
        p = f"model.layers.{l}."
        arrays += [
            (f"blk.{l}.attn_q.weight",
             _gguf_permute(hf[p + "self_attn.q_proj.weight"], cfg.num_heads)),
            (f"blk.{l}.attn_k.weight",
             _gguf_permute(hf[p + "self_attn.k_proj.weight"],
                           cfg.num_kv_heads)),
            (f"blk.{l}.attn_v.weight", hf[p + "self_attn.v_proj.weight"]),
            (f"blk.{l}.attn_output.weight", hf[p + "self_attn.o_proj.weight"]),
            (f"blk.{l}.ffn_gate.weight", hf[p + "mlp.gate_proj.weight"]),
            (f"blk.{l}.ffn_up.weight", hf[p + "mlp.up_proj.weight"]),
            (f"blk.{l}.ffn_down.weight", hf[p + "mlp.down_proj.weight"]),
            (f"blk.{l}.attn_norm.weight", hf[p + "input_layernorm.weight"]),
            (f"blk.{l}.ffn_norm.weight",
             hf[p + "post_attention_layernorm.weight"]),
        ]
    path = tmp_path / "tiny.gguf"
    blobs = [b for b in _tok_metadata()]
    blobs[1] = _kv("llama.embedding_length", _T_U32, struct.pack("<I", H))
    _write_gguf_with_data(path, blobs, arrays)

    params = load_gguf_params(cfg, str(path), dtype="float32")
    ref = llama.params_from_state_dict(
        cfg, {k: jnp.asarray(v) for k, v in hf.items()}, "float32")
    import jax

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        params, ref,
    )
    # serving-shaped: a prefill runs
    ctx = llama.init_ctx(cfg, 1, 64)
    toks = jnp.asarray(np.arange(1, 17, dtype=np.int32))
    _, lg = llama.prefill(cfg, params, ctx, toks, jnp.int32(0),
                          jnp.int32(0), jnp.int32(16))
    assert np.isfinite(np.asarray(lg)).all()


def test_cli_serves_gguf_end_to_end(tmp_path):
    """`dynamo-tpu run in=text --model-path x.gguf out=tpu` serves a
    completion from a single GGUF file: config + tokenizer + dequantized
    weights all come from the container (round-4 rejected this path)."""
    import os
    import subprocess
    import sys

    import numpy as np

    cfg_vocab = len(VOCAB)
    rng = np.random.RandomState(3)
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny(dtype="float32")
    arrays = [
        ("token_embd.weight",
         rng.randn(cfg_vocab, cfg.hidden_size).astype(np.float32) * 0.02),
        ("output_norm.weight", np.ones(cfg.hidden_size, np.float32)),
        ("output.weight",
         rng.randn(cfg_vocab, cfg.hidden_size).astype(np.float32) * 0.02),
    ]
    for l in range(cfg.num_layers):
        s = 1.0 / np.sqrt(cfg.hidden_size)
        arrays += [
            (f"blk.{l}.attn_q.weight",
             rng.randn(cfg.q_dim, cfg.hidden_size).astype(np.float32) * s),
            (f"blk.{l}.attn_k.weight",
             rng.randn(cfg.kv_dim, cfg.hidden_size).astype(np.float32) * s),
            (f"blk.{l}.attn_v.weight",
             rng.randn(cfg.kv_dim, cfg.hidden_size).astype(np.float32) * s),
            (f"blk.{l}.attn_output.weight",
             rng.randn(cfg.hidden_size, cfg.q_dim).astype(np.float32) * s),
            (f"blk.{l}.ffn_gate.weight",
             rng.randn(cfg.intermediate_size, cfg.hidden_size).astype(np.float32) * s),
            (f"blk.{l}.ffn_up.weight",
             rng.randn(cfg.intermediate_size, cfg.hidden_size).astype(np.float32) * s),
            (f"blk.{l}.ffn_down.weight",
             rng.randn(cfg.hidden_size, cfg.intermediate_size).astype(np.float32) * s),
            (f"blk.{l}.attn_norm.weight", np.ones(cfg.hidden_size, np.float32)),
            (f"blk.{l}.ffn_norm.weight", np.ones(cfg.hidden_size, np.float32)),
        ]
    path = tmp_path / "served.gguf"
    _write_gguf_with_data(path, _tok_metadata(), arrays)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli", "run", "in=text",
         "out=tpu", "--model-path", str(path),
         "--prompt", "hello world", "--max-tokens", "4",
         "--page-size", "16", "--num-pages", "32",
         "--max-decode-slots", "2", "--cache-dtype", "float32"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert any(line.strip() for line in r.stdout.splitlines())
