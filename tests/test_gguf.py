"""GGUF reader tests (reference gguf/content.rs + gguf_tokenizer.rs:587):
a synthetic GGUF v3 file written by the test is read back — metadata,
tensor descriptors, ModelConfig extraction, and the SPM-unigram
tokenizer's encode/decode round trip."""
import struct

import pytest

from dynamo_tpu.gguf import GgufTokenizer, config_from_gguf, read_gguf

_T_U32, _T_F32, _T_BOOL, _T_STRING, _T_ARRAY = 4, 6, 7, 8, 9


def _s(x: str) -> bytes:
    b = x.encode()
    return struct.pack("<Q", len(b)) + b


def _kv(key: str, vtype: int, payload: bytes) -> bytes:
    return _s(key) + struct.pack("<I", vtype) + payload


def _arr(etype: int, items: list[bytes]) -> bytes:
    return struct.pack("<IQ", etype, len(items)) + b"".join(items)


def write_gguf(path, metadata_blobs: list[bytes], tensors=()):
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<IQQ", 3, len(tensors), len(metadata_blobs)))
        for blob in metadata_blobs:
            f.write(blob)
        for name, dims, dtype, off in tensors:
            f.write(_s(name))
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", dtype, off))


VOCAB = ["<unk>", "<s>", "</s>"]
VOCAB += [f"<0x{i:02X}>" for i in range(256)]
PIECES = ["▁hello", "▁world", "▁he", "llo", "▁wor", "ld", "▁", "h", "e",
          "l", "o", "w", "r", "d", "▁hi"]
VOCAB += PIECES
SCORES = [0.0] * 259 + [-1.0, -1.0, -3.0, -3.0, -3.0, -3.0, -5.0, -6.0,
                        -6.0, -6.0, -6.0, -6.0, -6.0, -6.0, -1.5]


def _tok_metadata() -> list[bytes]:
    return [
        _kv("general.architecture", _T_STRING, _s("llama")),
        _kv("llama.embedding_length", _T_U32, struct.pack("<I", 64)),
        _kv("llama.block_count", _T_U32, struct.pack("<I", 4)),
        _kv("llama.attention.head_count", _T_U32, struct.pack("<I", 4)),
        _kv("llama.attention.head_count_kv", _T_U32, struct.pack("<I", 2)),
        _kv("llama.feed_forward_length", _T_U32, struct.pack("<I", 128)),
        _kv("llama.context_length", _T_U32, struct.pack("<I", 512)),
        _kv("llama.rope.freq_base", _T_F32, struct.pack("<f", 10000.0)),
        _kv("llama.attention.layer_norm_rms_epsilon", _T_F32,
            struct.pack("<f", 1e-5)),
        _kv("tokenizer.ggml.model", _T_STRING, _s("llama")),
        _kv("tokenizer.ggml.tokens", _T_ARRAY,
            _arr(_T_STRING, [_s(t) for t in VOCAB])),
        _kv("tokenizer.ggml.scores", _T_ARRAY,
            _arr(_T_F32, [struct.pack("<f", s) for s in SCORES])),
        _kv("tokenizer.ggml.bos_token_id", _T_U32, struct.pack("<I", 1)),
        _kv("tokenizer.ggml.eos_token_id", _T_U32, struct.pack("<I", 2)),
        _kv("tokenizer.ggml.add_bos_token", _T_BOOL, b"\x01"),
    ]


def test_read_gguf_roundtrip(tmp_path):
    path = tmp_path / "m.gguf"
    write_gguf(path, _tok_metadata(),
               tensors=[("token_embd.weight", [64, len(VOCAB)], 0, 0),
                        ("blk.0.attn_q.weight", [64, 64], 0, 4096)])
    md, tensors = read_gguf(str(path))
    assert md["general.architecture"] == "llama"
    assert md["llama.block_count"] == 4
    assert len(md["tokenizer.ggml.tokens"]) == len(VOCAB)
    assert [t["name"] for t in tensors] == [
        "token_embd.weight", "blk.0.attn_q.weight"
    ]
    assert tensors[1]["offset"] == 4096

    cfg = config_from_gguf(md)
    assert cfg.num_layers == 4
    assert cfg.num_kv_heads == 2
    assert cfg.vocab_size == len(VOCAB)
    assert cfg.head_dim == 16


def test_gguf_rejects_non_gguf(tmp_path):
    p = tmp_path / "x.bin"
    p.write_bytes(b"NOTG" + b"\x00" * 64)
    with pytest.raises(ValueError, match="not a GGUF"):
        read_gguf(str(p))


def test_spm_tokenizer_encode_decode(tmp_path):
    path = tmp_path / "m.gguf"
    write_gguf(path, _tok_metadata())
    md, _ = read_gguf(str(path))
    tok = GgufTokenizer.from_metadata(md)

    ids = tok.encode("hello world")
    assert ids[0] == 1  # bos
    # unigram Viterbi picks the highest-scoring pieces
    assert [tok.tokens[i] for i in ids[1:]] == ["▁hello", "▁world"]
    assert tok.decode(ids) == "hello world"

    # piece preference follows scores: "hi" is a whole piece
    ids2 = tok.encode("hi")
    assert [tok.tokens[i] for i in ids2[1:]] == ["▁hi"]

    # byte fallback covers characters outside the vocab, losslessly
    ids3 = tok.encode("héllo")
    assert tok.decode(ids3) == "héllo"

    assert tok.stop_token_ids == [2]


def test_bpe_gguf_rejected(tmp_path):
    path = tmp_path / "m.gguf"
    blobs = _tok_metadata()
    blobs[9] = _kv("tokenizer.ggml.model", _T_STRING, _s("gpt2"))
    write_gguf(path, blobs)
    md, _ = read_gguf(str(path))
    with pytest.raises(ValueError, match="not supported"):
        GgufTokenizer.from_metadata(md)
