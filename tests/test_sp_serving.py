"""Sequence-parallel ring prefill IN THE SERVING PATH (VERDICT r3 #3:
sp_prefill must be reachable from TpuEngine, not dryrun-only): an engine on
an sp=8 CPU mesh routes long prompts through the whole-prompt ring pass and
produces the same tokens as the chunked local path."""
import numpy as np
import pytest

import jax

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

PS = 16


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=8):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


def mk_engine(cfg, params, **kw):
    ecfg = EngineConfig(
        num_pages=32, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64, 128),
        cache_dtype="float32", **kw.pop("ecfg_kw", {}),
    )
    return TpuEngine(cfg, ecfg, params=params, **kw)


async def test_sp_prefill_serves_long_prompt():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    prompt = list(range(1, 71))  # 70 tokens >= threshold

    ref_eng = mk_engine(cfg, params, mesh_config=MeshConfig(tp=1))
    ref = await collect(ref_eng, req_for(prompt))
    await ref_eng.stop()

    sp_eng = mk_engine(
        cfg, params, mesh_config=MeshConfig(sp=8),
        ecfg_kw=dict(sp_prefill_threshold=64),
    )
    out = await collect(sp_eng, req_for(prompt))
    assert sp_eng.sp_prefills == 1, "long prompt must take the sp path"
    assert out == ref, "ring prefill must serve the same tokens"

    # short prompts stay on the chunked local path
    short = await collect(sp_eng, req_for(list(range(1, 20))))
    assert sp_eng.sp_prefills == 1
    assert len(short) == 8

    # prompt blocks computed by the ring pass are sealed into the prefix
    # cache: a resend prefix-hits and stays bit-exact
    hits0 = sp_eng.allocator.hit_blocks
    out2 = await collect(sp_eng, req_for(prompt))
    assert out2 == ref
    assert sp_eng.allocator.hit_blocks > hits0
    await sp_eng.stop()
