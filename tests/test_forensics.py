"""Tail-latency forensics: SLO-breach dossiers, OpenMetrics exemplars,
and the fleet-merged latency feed.

Covers the full capture chain — breach detection at request finish,
trace promotion, dossier assembly into the bounded /debug/outliers ring,
exemplar-tagged histogram buckets that resolve back to servable
dossiers — plus the FleetLatencyFeed merge/delta math the planner's
latency trigger consumes, and the ≤5% always-on overhead bound.
"""
import re
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.backend import Backend
from dynamo_tpu.engines import EchoEngine
from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.telemetry import TelemetryRegistry, request_histograms
from dynamo_tpu.telemetry import metrics as tmetrics
from dynamo_tpu.telemetry.fleet_feed import FleetLatencyFeed
from dynamo_tpu.telemetry.forensics import (
    OUTLIERS,
    DossierRing,
    ForensicsCapture,
)
from dynamo_tpu.telemetry.trace import TRACES, Span, TraceStore
from dynamo_tpu.tokenizer import make_test_tokenizer

WORDS = [f"w{i}" for i in range(50)] + ["hello", "world"]

TTFT = "dynamo_request_ttft_seconds"
QUEUE = "dynamo_request_queue_seconds"
FLEET_TTFT = "dynamo_fleet_request_ttft_seconds"
FLEET_QUEUE = "dynamo_fleet_request_queue_seconds"


def make_forensic_service(**svc_kwargs) -> HttpService:
    tok = make_test_tokenizer(WORDS)
    fmt = PromptFormatter(
        template="{% for m in messages %}{{ m.content }} {% endfor %}"
    )
    chain = ModelChain(
        name="echo",
        preprocessor=OpenAIPreprocessor(
            tokenizer=tok, formatter=fmt, model_name="echo"),
        engine=EchoEngine(delay_s=0.0),
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    return HttpService(manager, **svc_kwargs)


async def with_client(svc):
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    return client


def engine_metrics(worker: str, ttft_s: float, n: int = 8,
                   usage: float = 0.2) -> ForwardPassMetrics:
    """A worker metrics payload whose histograms show ``n`` requests at
    ``ttft_s`` TTFT/queue-wait — canonical ladder via request_histograms
    so fleet merge sums bucket-for-bucket."""
    t = request_histograms(TelemetryRegistry(), engine=True)
    for _ in range(n):
        t.get(TTFT).observe(ttft_s)
        t.get(QUEUE).observe(ttft_s)
    return ForwardPassMetrics(
        worker_id=worker,
        worker_stats=WorkerStats(request_active_slots=1,
                                 request_total_slots=8),
        kv_stats=KvStats(gpu_cache_usage_perc=usage),
        histograms=t.snapshot(),
    )


# ---------------------------------------------------------------------------
# exemplars


def test_exemplar_rendered_openmetrics_only():
    reg = request_histograms(TelemetryRegistry())
    reg.get(TTFT).observe(0.07, exemplar_id="req-abc")
    om = "\n".join(reg.get(TTFT).render(openmetrics=True))
    m = re.search(
        r'_bucket\{le="[^"]+"\} \d+ # \{trace_id="req-abc"\} '
        r'([0-9.e+-]+) ([0-9.]+)', om)
    assert m, f"no OpenMetrics exemplar in:\n{om}"
    assert float(m.group(1)) == pytest.approx(0.07)
    # plain Prometheus scrape stays exemplar-free
    plain = "\n".join(reg.get(TTFT).render())
    assert "# {" not in plain


def test_plain_render_byte_identical_with_exemplars():
    """Attaching exemplars must not perturb the plain text format."""
    a = request_histograms(TelemetryRegistry())
    b = request_histograms(TelemetryRegistry())
    for v in (0.01, 0.3, 2.0):
        a.get(TTFT).observe(v, exemplar_id=f"r-{v}")
        b.get(TTFT).observe(v)
    assert a.get(TTFT).render() == b.get(TTFT).render()


def test_exemplar_survives_snapshot_round_trip():
    reg = request_histograms(TelemetryRegistry())
    reg.get(TTFT).observe(0.2, exemplar_id="rid-9")
    snap = reg.get(TTFT).snapshot()
    ex = snap.get("exemplars") or {}
    assert any(e[0] == "rid-9" for e in ex.values())
    # snapshot is JSON-shaped: string keys, [id, value, ts] triples
    for k, e in ex.items():
        assert isinstance(k, str)
        assert len(e) == 3


# ---------------------------------------------------------------------------
# fleet-merged latency feed


def test_fleet_merge_equals_sum():
    feed = FleetLatencyFeed()
    m0 = engine_metrics("w0", 0.05, n=5)
    m1 = engine_metrics("w1", 1.5, n=3)
    feed.observe(m0)
    feed.observe(m1)
    assert sorted(feed.workers()) == ["w0", "w1"]
    merged = feed.merged()
    fleet = merged[FLEET_TTFT]
    s0 = m0.histograms[TTFT]
    s1 = m1.histograms[TTFT]
    assert fleet["count"] == s0["count"] + s1["count"] == 8
    assert fleet["sum"] == pytest.approx(s0["sum"] + s1["sum"])
    assert fleet["buckets"] == s0["buckets"]
    for i, c in enumerate(fleet["counts"]):
        assert c == s0["counts"][i] + s1["counts"][i]
    # and the percentile helper reads the merged distribution
    p99 = feed.percentile(FLEET_TTFT, 0.99)
    assert p99 is not None and p99 > 0.5


def test_fleet_feed_interval_deltas():
    """advance() returns per-interval deltas, not lifetime cumulatives —
    an hour of healthy history must not dilute a fresh latency wave."""
    feed = FleetLatencyFeed()
    feed.observe(engine_metrics("w0", 0.01, n=100))
    first = feed.advance()
    assert first[FLEET_TTFT]["count"] == 100
    # next interval: 10 slow requests on top of the same worker
    t = request_histograms(TelemetryRegistry(), engine=True)
    for _ in range(100):
        t.get(TTFT).observe(0.01)
        t.get(QUEUE).observe(0.01)
    for _ in range(10):
        t.get(TTFT).observe(2.0)
        t.get(QUEUE).observe(2.0)
    feed.observe(ForwardPassMetrics(worker_id="w0",
                                    histograms=t.snapshot()))
    delta = feed.advance()
    assert delta[FLEET_TTFT]["count"] == 10
    p99 = tmetrics.percentile_from_snapshot(delta[FLEET_TTFT], 0.99)
    assert p99 is not None and p99 > 1.0


def test_fleet_feed_staleness_eviction():
    now = [0.0]
    feed = FleetLatencyFeed(stale_after_s=5.0, clock=lambda: now[0])
    feed.observe(engine_metrics("w0", 0.1))
    assert feed.workers() == ["w0"]
    now[0] = 10.0
    assert feed.workers() == []
    assert FLEET_TTFT not in feed.merged()


def test_fleet_feed_render_has_help_type():
    feed = FleetLatencyFeed()
    feed.observe(engine_metrics("w0", 0.1))
    text = feed.render()
    assert f"# TYPE {FLEET_TTFT} histogram" in text
    assert f"# HELP {FLEET_TTFT}" in text
    assert "dynamo_fleet_feed_workers 1" in text
    om = feed.render(openmetrics=True)
    assert f"# TYPE {FLEET_TTFT} histogram" in om


# ---------------------------------------------------------------------------
# dossier ring + trace 404 taxonomy


def _capture(fc: ForensicsCapture, rid: str) -> None:
    fc.capture_direct(
        rid, "ttft_breach", {"ttft_s": 1.0, "e2e_s": 2.0}, "w0",
        {"trace_id": rid, "finished": True,
         "spans": [{"name": "prefill", "start_s": 1.0,
                    "duration_s": 0.5}]},
    )


def test_dossier_ring_bounded_eviction():
    ring = DossierRing(capacity=2)
    fc = ForensicsCapture(ring, ttft_target_s=0.5, itl_target_s=10.0,
                          traces=TraceStore())
    for rid in ("r0", "r1", "r2"):
        _capture(fc, rid)
    assert ring.get("r0") is None          # oldest evicted
    assert ring.get("r2") is not None
    assert ring.evicted_total == 1
    assert ring.captured_total == 3
    idx = ring.index()
    assert idx["capacity"] == 2
    assert [o["request_id"] for o in idx["outliers"]] == ["r2", "r1"]
    assert ring.oldest_id() == "r1"


def test_trace_404_distinguishes_evicted_vs_unsampled_vs_never_seen():
    store = TraceStore(max_completed=1)
    # unsampled shell, finished without promotion
    store.start("shell", sampled=False)
    store.finish("shell")
    assert store.describe_missing("shell")["reason"] == "unsampled"
    # two sampled finishes through a 1-slot ring: first one evicted
    store.start("old", sampled=True)
    store.finish("old")
    store.start("new", sampled=True)
    store.finish("new")
    gone = store.describe_missing("old")
    assert gone["reason"] == "evicted"
    assert gone["ring_capacity"] == 1
    assert gone["oldest_retained_id"] == "new"
    assert gone["evicted_total"] == 1
    assert store.describe_missing("ghost")["reason"] == "never_seen"


def test_worker_finish_one_shot_capture():
    ring = DossierRing(capacity=8)
    fc = ForensicsCapture(ring, ttft_target_s=0.1, itl_target_s=10.0,
                          traces=TraceStore())
    d = fc.worker_finish(
        "wr-1",
        timing={"ttft_s": 0.5, "e2e_s": 1.0, "queue_s": 0.2},
        worker_id="w3",
        trace_spans=[
            {"name": "queue", "start_s": 0.0, "duration_s": 0.2},
            {"name": "prefill", "start_s": 0.2, "duration_s": 0.3},
        ],
    )
    assert d is not None and d.reason == "ttft_breach"
    got = ring.get("wr-1")
    assert got is not None
    assert got.worker_id == "w3"
    assert len(got.trace["spans"]) == 2
    assert got.kv_path["queue_wait_s"] == pytest.approx(0.2)
    # healthy request: no dossier
    assert fc.worker_finish(
        "wr-2", timing={"ttft_s": 0.01, "e2e_s": 0.02},
        worker_id="w3", trace_spans=[]) is None
    assert ring.get("wr-2") is None


def test_shell_trace_promoted_on_breach():
    """The sampled=False shell path: buffered spans survive a
    finish-time promotion triggered by on_finish."""
    store = TraceStore()
    ring = DossierRing(capacity=8)
    fc = ForensicsCapture(ring, ttft_target_s=0.1, itl_target_s=10.0,
                          traces=store)
    store.start("breach-1", sampled=False)
    store.add_span("breach-1", Span(
        name="http", start_s=time.time(), duration_s=0.4))
    assert fc.on_finish("breach-1", ttft_s=0.9) == "ttft_breach"
    assert fc.pending("breach-1")
    tr = store.finish("breach-1")
    d = fc.on_trace_finished("breach-1", tr)
    assert d is not None
    assert d.trace["trace_id"] == "breach-1"
    assert [s["name"] for s in d.trace["spans"]] == ["http"]


# ---------------------------------------------------------------------------
# overhead: the always-on finish path must stay cheap


def test_on_finish_no_capture_overhead_under_budget():
    """Per-finish cost of the breach check on a healthy request must be
    ≤5% of a 1 ms request budget (it is a couple of float compares)."""
    fc = ForensicsCapture(DossierRing(capacity=4), ttft_target_s=10.0,
                          itl_target_s=10.0, traces=TraceStore())
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        fc.on_finish(f"r{i}", ttft_s=0.01, itl_p95_s=0.001, e2e_s=0.1,
                     queue_s=0.0)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 50e-6, f"on_finish {per_call*1e6:.1f}us/call"


# ---------------------------------------------------------------------------
# planner: fleet-merged latency trigger


class FakeConnector:
    def __init__(self, n=1):
        self.n = n

    def current_replicas(self):
        return self.n

    async def set_replicas(self, n):
        self.n = n


def test_planner_fleet_latency_wave_triggers_scale_up():
    """A latency wave with calm stream counts: the merged-feed arm
    scales up; the stream-count-only arm misses it."""
    from dynamo_tpu.planner import Planner, PlannerConfig

    def build(fleet_ttft_s: float) -> Planner:
        return Planner(None, FakeConnector(1), PlannerConfig(
            kv_usage_scale_up=0.8, kv_usage_scale_down=0.01,
            waiting_scale_up=100, min_replicas=1, max_replicas=4,
            fleet_ttft_scale_up_s=fleet_ttft_s,
        ))

    # wave: 20 requests at 1s TTFT, but no queue depth / KV pressure
    wave = engine_metrics("w0", 1.0, n=20, usage=0.2)

    feed_arm = build(0.3)
    feed_arm.aggregator.update(wave)
    feed_arm.fleet_feed.observe(wave)
    assert feed_arm.decide() == 2          # merged feed sees the wave

    stream_arm = build(0.0)
    stream_arm.aggregator.update(wave)
    stream_arm.fleet_feed.observe(wave)
    assert stream_arm.decide() == 1        # stream counts look calm

    # and the trigger publishes its gauge for scrape-side visibility
    from dynamo_tpu.planner_metrics import PLANNER
    assert "dynamo_planner_fleet_ttft_p99_seconds" in PLANNER.render()


def test_planner_fleet_queue_trigger():
    from dynamo_tpu.planner import Planner, PlannerConfig

    planner = Planner(None, FakeConnector(1), PlannerConfig(
        kv_usage_scale_up=0.8, kv_usage_scale_down=0.01,
        waiting_scale_up=100, min_replicas=1, max_replicas=4,
        fleet_queue_scale_up_s=0.5,
    ))
    wave = engine_metrics("w0", 2.0, n=20)
    planner.aggregator.update(wave)
    planner.fleet_feed.observe(wave)
    assert planner.decide() == 2


# ---------------------------------------------------------------------------
# e2e: breach -> dossier over a live frontend


async def test_breach_to_dossier_e2e():
    """Every SLO-breaching request yields a servable dossier joining its
    span tree and timing under one trace_id, discoverable through the
    exemplar on the TTFT histogram bucket."""
    TRACES.clear()
    OUTLIERS.clear()
    svc = make_forensic_service()
    svc.forensics._ttft_target_s = 0.0     # any TTFT breaches
    client = await with_client(svc)
    try:
        r = await client.post("/v1/chat/completions", json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 2,
        })
        assert r.status == 200

        # the dossier ring lists the breach, newest first
        r = await client.get("/debug/outliers")
        idx = await r.json()
        assert idx["captured_total"] >= 1
        assert idx["outliers"], idx
        entry = idx["outliers"][0]
        rid = entry["request_id"]
        assert entry["reason"] == "ttft_breach"

        # the full dossier joins trace + timing under that trace_id
        r = await client.get(f"/debug/outliers/{rid}")
        assert r.status == 200
        d = await r.json()
        assert d["request_id"] == rid
        assert d["trace"]["trace_id"] == rid
        assert d["timing"]["ttft_s"] >= 0.0
        assert "e2e_s" in d["timing"]
        assert d["trace"]["spans"], "dossier lost the span tree"

        # perfetto export of the same dossier
        r = await client.get(f"/debug/outliers/{rid}?format=perfetto")
        perfetto = await r.json()
        assert perfetto["traceEvents"]

        # OpenMetrics scrape: the TTFT bucket exemplar carries the rid
        # and resolves to the servable dossier above
        r = await client.get("/metrics", headers={
            "Accept": "application/openmetrics-text"})
        text = await r.text()
        assert text.rstrip().endswith("# EOF")
        assert f'# {{trace_id="{rid}"}}' in text
        assert "dynamo_request_ttft_seconds_bucket" in text

        # plain Prometheus scrape stays exemplar-free
        r = await client.get("/metrics")
        plain = await r.text()
        assert "# {" not in plain
        assert "# EOF" not in plain
        # fleet + forensics families render on the frontend surface
        assert "dynamo_forensics_dossiers_total" in plain
        assert "dynamo_fleet_feed_workers" in plain
    finally:
        await client.close()
        OUTLIERS.clear()
        TRACES.clear()


async def test_outlier_404_and_trace_404_bodies():
    TRACES.clear()
    OUTLIERS.clear()
    svc = make_forensic_service()
    client = await with_client(svc)
    try:
        r = await client.get("/debug/outliers/ghost")
        assert r.status == 404
        body = await r.json()
        assert body["capacity"] == OUTLIERS.capacity
        assert "oldest_retained_id" in body

        r = await client.get("/debug/trace/ghost")
        assert r.status == 404
        body = await r.json()
        assert body["reason"] == "never_seen"
        assert body["ring_capacity"] == TRACES.max_completed
    finally:
        await client.close()


async def test_healthy_requests_not_captured():
    """With sane targets and no sampling, a fast request leaves no
    dossier — the capture path stays dormant."""
    TRACES.clear()
    OUTLIERS.clear()
    svc = make_forensic_service()
    svc.forensics._ttft_target_s = 60.0
    svc.forensics._itl_target_s = 60.0
    client = await with_client(svc)
    try:
        r = await client.post("/v1/chat/completions", json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 2,
        })
        assert r.status == 200
        r = await client.get("/debug/outliers")
        idx = await r.json()
        assert idx["outliers"] == []
    finally:
        await client.close()
        OUTLIERS.clear()
        TRACES.clear()


async def test_sample_rate_captures_healthy_request():
    """--forensics-sample-rate 1.0: healthy requests get dossiers tagged
    'sampled' (the comparison baseline)."""
    TRACES.clear()
    OUTLIERS.clear()
    svc = make_forensic_service(forensics_sample_rate=1.0)
    svc.forensics._ttft_target_s = 60.0
    svc.forensics._itl_target_s = 60.0
    client = await with_client(svc)
    try:
        r = await client.post("/v1/chat/completions", json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 2,
        })
        assert r.status == 200
        r = await client.get("/debug/outliers")
        idx = await r.json()
        assert idx["outliers"]
        assert idx["outliers"][0]["reason"] == "sampled"
    finally:
        await client.close()
        OUTLIERS.clear()
        TRACES.clear()
