"""Metrics contract: every ``dynamo_*`` series rendered by the system
server, the aggregating exporter, and the frontend must carry HELP/TYPE
metadata and be documented in README's Observability section — the
scrape surfaces and the docs cannot drift apart silently.
"""
import os
import re

from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.telemetry import TelemetryRegistry, request_histograms

README = os.path.join(os.path.dirname(__file__), "..", "README.md")

# sample-name suffixes that belong to a declared family rather than
# being families themselves (histogram series + prometheus_client extras)
_SUFFIXES = ("_bucket", "_sum", "_count", "_total", "_created")


class _StubEngine:
    """Engine double: gauges + populated histogram snapshots."""

    def __init__(self):
        self.telemetry = request_histograms(TelemetryRegistry(),
                                            engine=True)
        for h in ("dynamo_request_ttft_seconds",
                  "dynamo_request_itl_seconds"):
            self.telemetry.get(h).observe(0.1)

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            worker_id="w0",
            worker_stats=WorkerStats(request_active_slots=1,
                                     request_total_slots=4),
            kv_stats=KvStats(kv_active_blocks=2, kv_total_blocks=8),
            histograms=self.telemetry.snapshot(),
        )


def _parse_families(text: str):
    """(declared families with both HELP and TYPE, sample names)."""
    helped, typed, samples = set(), set(), set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split()[2])
        elif not line.startswith("#"):
            samples.add(re.split(r"[{ ]", line, 1)[0])
    return helped & typed, samples


def _families_of(samples, declared):
    """Map each sample name onto its declared family (or fail)."""
    out = {}
    for s in samples:
        fam = None
        if s in declared:
            fam = s
        else:
            for suf in _SUFFIXES:
                if s.endswith(suf) and s[: -len(suf)] in declared:
                    fam = s[: -len(suf)]
                    break
                # prometheus_client counters: family "x_total" declares
                # HELP/TYPE as x_total but _created samples are x_created
                if s.endswith("_created") and (
                    s[: -len("_created")] + "_total" in declared
                ):
                    fam = s[: -len("_created")] + "_total"
                    break
        assert fam is not None, f"sample {s!r} has no HELP/TYPE family"
        out[s] = fam
    return out


def _assert_contract(text: str, readme: str):
    declared, samples = _parse_families(text)
    fams = _families_of(samples, declared)
    for fam in set(fams.values()):
        # prometheus_client exposes the *_created companion series as its
        # own gauge family — documentation-wise it's part of the parent
        if fam.endswith("_created"):
            fam = fam[: -len("_created")]
        if fam.startswith("dynamo_"):
            assert fam in readme, f"{fam} not documented in README"


def _readme_text() -> str:
    with open(README) as f:
        return f.read()


def test_system_server_render_contract():
    from dynamo_tpu.runtime.system_server import SystemServer

    text = SystemServer(_StubEngine(), worker_id="w0").render()
    # histograms made it into the per-worker render, worker-labelled
    assert 'dynamo_request_ttft_seconds_bucket{worker="w0",le=' in text
    _assert_contract(text, _readme_text())


def test_exporter_render_contract():
    from dynamo_tpu.metrics_exporter import MetricsExporter

    exp = MetricsExporter(kv=None)
    m = _StubEngine().metrics()
    exp.aggregator.update(m)
    m2 = _StubEngine().metrics()
    m2.worker_id = "w1"
    exp.aggregator.update(m2)
    text = exp.render()
    # the satellite fix: dynamo_metrics_workers now has HELP/TYPE
    assert "# HELP dynamo_metrics_workers" in text
    assert "# TYPE dynamo_metrics_workers gauge" in text
    assert "dynamo_metrics_workers 2" in text
    # one HELP/TYPE head per histogram family, both workers' series
    assert text.count("# TYPE dynamo_request_ttft_seconds histogram") == 1
    assert 'dynamo_request_ttft_seconds_count{worker="w0"}' in text
    assert 'dynamo_request_ttft_seconds_count{worker="w1"}' in text
    _assert_contract(text, _readme_text())


def test_frontend_render_contract():
    from dynamo_tpu.frontend.service import HttpService

    svc = HttpService()
    svc.metrics.requests_total.labels("m", "chat_completions", "200").inc()
    svc.metrics.duration.labels("m").observe(0.1)
    svc._h_ttft.observe(0.05)
    text = svc.metrics.render().decode() + svc.telemetry.render()
    _assert_contract(text, _readme_text())


def test_readme_documents_canonical_series():
    readme = _readme_text()
    for name in (
        "dynamo_request_ttft_seconds", "dynamo_request_itl_seconds",
        "dynamo_request_e2e_seconds", "dynamo_request_queue_seconds",
        "dynamo_engine_round_seconds", "dynamo_spec_acceptance_rate",
        "dynamo_spec_effective_k", "dynamo_metrics_workers",
        # KV-transfer data plane (chunk pipeline) + disagg fallback
        "dynamo_kv_transfer_tx_chunks_total",
        "dynamo_kv_transfer_rx_chunks_total",
        "dynamo_kv_transfer_tx_bytes_total",
        "dynamo_kv_transfer_rx_bytes_total",
        "dynamo_kv_transfer_streams_total",
        "dynamo_kv_transfer_errors_total",
        "dynamo_kv_transfer_chunk_seconds",
        "dynamo_kv_transfer_seconds",
        "dynamo_disagg_fallback_total",
        # int8 KV-block economy (dynamo_tpu/kv_quant.py)
        "dynamo_kv_quant_pages_total",
        "dynamo_kv_quant_dequant_pages_total",
        "dynamo_kv_quant_scale_bytes_total",
        "dynamo_kv_quant_dequant_seconds",
        "dynamo_kv_pool_capacity_blocks",
        # in-kernel int8 decode ctx (PR 14: raw pool<->ctx copies +
        # once-per-round ring-flush requantize)
        "dynamo_kv_quant_ctx_seal_raw_pages_total",
        "dynamo_kv_quant_ctx_admit_raw_pages_total",
        "dynamo_kv_quant_ctx_flush_groups_total",
        # KV data-integrity plane (dynamo_tpu/kv_integrity.py)
        "dynamo_kv_integrity_verified_total",
        "dynamo_kv_integrity_failed_total",
        "dynamo_kv_integrity_quarantined_total",
        "dynamo_kv_integrity_recomputed_total",
        "dynamo_kv_integrity_retries_total",
        "dynamo_kv_integrity_g3_scrub_recovered_total",
        "dynamo_kv_integrity_g3_scrub_dropped_total",
        # overload-protection plane (dynamo_tpu/overload/)
        "dynamo_overload_rejected_total",
        "dynamo_overload_shed_total",
        "dynamo_overload_preempted_total",
        "dynamo_overload_preempt_migrations_total",
        "dynamo_overload_http_429_total",
        "dynamo_overload_router_spills_total",
        "dynamo_overload_queue_depth",
        "dynamo_overload_queue_tokens",
        "dynamo_worker_waiting_prefill_tokens",
        "dynamo_worker_max_waiting_requests",
        "dynamo_worker_max_waiting_prefill_tokens",
        # performance-attribution plane (dynamo_tpu/telemetry/prof.py)
        "dynamo_host_round_seconds",
        "dynamo_host_round_coverage_ratio",
        "dynamo_slo_ttft_burn_rate",
        "dynamo_slo_itl_burn_rate",
        # tail-latency forensics (dynamo_tpu/telemetry/forensics.py)
        "dynamo_forensics_dossiers_total",
        "dynamo_forensics_breaches_total",
        "dynamo_forensics_sampled_total",
        "dynamo_forensics_dossiers_evicted_total",
        "dynamo_forensics_ring_size",
        # fleet-merged latency feed (dynamo_tpu/telemetry/fleet_feed.py)
        "dynamo_fleet_request_ttft_seconds",
        "dynamo_fleet_request_itl_seconds",
        "dynamo_fleet_request_e2e_seconds",
        "dynamo_fleet_request_queue_seconds",
        "dynamo_fleet_engine_round_seconds",
        "dynamo_fleet_feed_workers",
        "dynamo_planner_fleet_ttft_p99_seconds",
        "dynamo_planner_fleet_queue_p99_seconds",
        # tenant-sliced serving plane (dynamo_tpu/tenancy/)
        "dynamo_tenant_admitted_total",
        "dynamo_tenant_rejected_total",
        "dynamo_tenant_shed_total",
        "dynamo_tenant_http_429_total",
        "dynamo_tenant_queue_depth",
        "dynamo_tenant_queue_tokens",
        "dynamo_tenant_adapter_rounds_total",
        "dynamo_tenant_request_ttft_seconds",
        "dynamo_tenant_request_queue_seconds",
    ):
        assert name in readme, f"{name} missing from README"
    for endpoint in ("/debug/trace", "/debug/flight", "/debug/prof",
                     "/debug/outliers", "/debug/tenants"):
        assert endpoint in readme


def test_forensics_and_fleet_families_on_all_three_surfaces():
    """The new forensics counters and the fleet-merged histograms render
    with HELP/TYPE on every scrape surface."""
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.system_server import SystemServer

    from dynamo_tpu.telemetry.fleet_feed import FLEET_FEED
    from dynamo_tpu.telemetry.forensics import FORENSICS

    exp = MetricsExporter(kv=None)
    exp.aggregator.update(_StubEngine().metrics())
    svc = HttpService()
    frontend = (svc.metrics.render().decode() + svc.telemetry.render()
                + FLEET_FEED.render() + FORENSICS.render())
    for text in (
        SystemServer(_StubEngine(), worker_id="w0").render(),
        exp.render(),
        frontend,
    ):
        assert "# TYPE dynamo_forensics_dossiers_total counter" in text
        assert "# TYPE dynamo_forensics_ring_size gauge" in text
        assert "# TYPE dynamo_fleet_feed_workers gauge" in text


def test_tenant_families_on_all_three_surfaces():
    """The tenant-sliced families render — with HELP/TYPE and the
    ``tenant`` label — on every scrape surface."""
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.system_server import SystemServer
    from dynamo_tpu.tenancy import TENANT

    TENANT.inc("dynamo_tenant_admitted_total", "t0")
    TENANT.observe("dynamo_tenant_request_ttft_seconds", "t0", 0.05)
    try:
        exp = MetricsExporter(kv=None)
        exp.aggregator.update(_StubEngine().metrics())
        svc = HttpService()
        frontend = (svc.metrics.render().decode() + svc.telemetry.render()
                    + TENANT.render())
        for text in (
            SystemServer(_StubEngine(), worker_id="w0").render(),
            exp.render(),
            frontend,
        ):
            assert "# TYPE dynamo_tenant_admitted_total counter" in text
            assert "# TYPE dynamo_tenant_queue_depth gauge" in text
            assert ("# TYPE dynamo_tenant_request_ttft_seconds histogram"
                    in text)
            assert text.count(
                "# TYPE dynamo_tenant_admitted_total counter") == 1
            assert 'dynamo_tenant_admitted_total{tenant="t0"} 1' in text
            assert ('dynamo_tenant_request_ttft_seconds_bucket{tenant="t0"'
                    in text)
            _assert_contract(text, _readme_text())
    finally:
        TENANT.reset()


def test_prof_families_on_all_three_surfaces():
    """The attribution plane's families render — with HELP/TYPE and the
    per-segment label — on every scrape surface."""
    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.system_server import SystemServer
    from dynamo_tpu.telemetry.prof import PROF, SEGMENTS, RoundProf

    prof = RoundProf()
    prof.begin_round()
    prof.enter(SEGMENTS.index("dispatch"))
    prof.end_round()
    PROF.fold(prof)
    try:
        for text in (
            SystemServer(_StubEngine(), worker_id="w0").render(),
            MetricsExporter(kv=None).render(),
        ):
            assert "# TYPE dynamo_host_round_seconds histogram" in text
            assert text.count(
                "# TYPE dynamo_host_round_seconds histogram") == 1
            assert 'dynamo_host_round_seconds_bucket{segment=' in text
            assert "# TYPE dynamo_host_round_coverage_ratio gauge" in text
            assert "# TYPE dynamo_slo_ttft_burn_rate gauge" in text
            assert "# TYPE dynamo_slo_itl_burn_rate gauge" in text
            _assert_contract(text, _readme_text())
        from dynamo_tpu.frontend.service import HttpService

        svc = HttpService()
        text = svc.telemetry.render() + PROF.render()
        assert "# TYPE dynamo_host_round_seconds histogram" in text
        _assert_contract(text, _readme_text())
    finally:
        PROF.reset()
