"""KV data-integrity plane tests (kv_integrity.py).

Keystones: (1) injected corruption at every tier boundary — a G2/G3
bit-flip, a torn G3 file, a corrupted wire frame — is DETECTED by the
content checksums, the poisoned block is quarantined, and the stream's
output stays token-identical to the clean run (corruption costs latency,
never wrong tokens); (2) the G3 disk tier is crash-consistent: a
snapshot of its mid-life on-disk state (pool + journal manifest)
reattaches on a fresh engine, the startup scrub recovers fully-written
blocks and drops torn entries as plain misses.
"""
import asyncio
import importlib.util
import json
import os
import shutil
import time
from dataclasses import replace

import numpy as np
import pytest

from dynamo_tpu.config import load_config
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.offload import DiskOffloadTier, HostOffloadTier
from dynamo_tpu.kv_integrity import (
    KV_INTEGRITY,
    KvIntegrityError,
    KvQuarantine,
    page_checksum,
    page_checksums,
    verify_wire_payload,
)
from dynamo_tpu.kv_quant import QuantizedPages
from dynamo_tpu.kv_transfer import (
    BlockTransferServer,
    encode_frame2,
    read_frame2,
    read_remote_pages,
    write_pages_stream,
    write_remote_pages,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.resilience.chaos import CHAOS
from dynamo_tpu.tokens import TokenBlockSequence

PS = 16
SHAPE = (2, 2, 1, PS, 4)  # (2, L, kvh, ps, hd)


@pytest.fixture(autouse=True)
def _clean_chaos():
    CHAOS.reset()
    yield
    CHAOS.reset()


def _pages(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(
        SHAPE[:3] + (n,) + SHAPE[3:]
    ).astype(np.float32)


# ---------------------------------------------------------------------------
# checksum primitives


def test_page_checksum_layout_invariant_and_sensitive():
    batch = _pages(3)
    # a strided pool slice and its dense copy must agree (tobytes is
    # C-order regardless of strides)
    assert page_checksum(batch[:, :, :, 1]) == page_checksum(
        np.ascontiguousarray(batch[:, :, :, 1])
    )
    crcs = page_checksums(batch)
    assert len(crcs) == 3 and len(set(crcs)) == 3
    # one flipped bit anywhere changes the page's checksum
    dirty = batch.copy()
    dirty.view(np.uint8).reshape(-1)[123] ^= 1
    assert page_checksums(dirty) != crcs


def test_page_checksums_cover_int8_scales():
    data = np.arange(2 * 2 * 1 * 2 * PS * 4, dtype=np.int8).reshape(
        2, 2, 1, 2, PS, 4
    )
    scales = np.ones((2, 2, 2), np.float32)
    q = QuantizedPages(data=data, scales=scales)
    crcs = page_checksums(q)
    # a flipped SCALE must fail verification exactly like a payload bit
    bad = QuantizedPages(data=data, scales=scales.copy())
    bad.scales[0, 0, 1] = 2.0
    crcs2 = page_checksums(bad)
    assert crcs2[0] == crcs[0] and crcs2[1] != crcs[1]


def test_verify_wire_payload_typed_error():
    batch = _pages(2, seed=1)
    header = {"kv_crc": page_checksums(batch)}
    verify_wire_payload(header, batch)  # clean: no raise
    verify_wire_payload({}, batch)  # pre-integrity peer: passes
    dirty = batch.copy()
    dirty[:, :, :, 1] += 1.0
    with pytest.raises(KvIntegrityError) as ei:
        verify_wire_payload(header, dirty, context="test")
    assert ei.value.bad_pages == (1,)


def test_quarantine_ttl_and_cap():
    q = KvQuarantine(ttl_s=0.05, max_entries=4)
    assert q.add(7) is True
    assert q.add(7) is False  # no double count
    assert 7 in q and len(q) == 1
    time.sleep(0.06)
    assert 7 not in q and len(q) == 0  # TTL lapsed: readmittable
    # capacity cap bounds memory under a corruption storm
    assert q.add_all(range(10)) == 10
    assert len(q) <= 4


# ---------------------------------------------------------------------------
# chaos injection points


def test_chaos_grammar_parses_integrity_points():
    CHAOS.configure("flip_kv_bits:p=0.5,corrupt_frame:once,truncate_g3")
    assert CHAOS.points["flip_kv_bits"].armed
    assert CHAOS.points["flip_kv_bits"].probability == 0.5
    assert CHAOS.points["corrupt_frame"].once
    assert CHAOS.points["truncate_g3"].armed


def test_flip_kv_bits_mutates_each_page():
    CHAOS.arm("flip_kv_bits", probability=1.0)
    batch = _pages(3, seed=2)
    clean = batch.copy()
    assert CHAOS.maybe_flip_bits(batch) == 3
    for i in range(3):
        assert not np.array_equal(batch[:, :, :, i], clean[:, :, :, i])


def test_corrupt_frame_hits_copy_not_source():
    CHAOS.arm("corrupt_frame", once=True)
    payload = _pages(1, seed=3)
    clean = payload.copy()
    dirty = CHAOS.maybe_corrupt_frame(payload)
    assert not np.array_equal(dirty, clean)
    np.testing.assert_array_equal(payload, clean)  # source untouched
    # once-fuse consumed: next call passes through
    assert CHAOS.maybe_corrupt_frame(payload) is payload


# ---------------------------------------------------------------------------
# tier verify + quarantine


def test_tier_verify_detects_corruption_and_quarantine_refuses():
    q = KvQuarantine()
    t = HostOffloadTier(4, SHAPE, np.float32, quarantine=q)
    batch = _pages(3, seed=4)
    assert t.put_batch([1, 2, 3], [0, 1, 2], batch) == 3
    got = t.gather([1, 2, 3])
    assert t.verify_pages([1, 2, 3], got) == []
    got[:, :, :, 1] += 1.0  # in-flight rot on the gathered copy
    assert t.verify_pages([1, 2, 3], got) == [1]
    # quarantined hashes are refused re-admission and dropped everywhere
    q.add(2)
    t.drop_everywhere(2)
    assert 2 not in t
    assert t.put_one(2, 1, batch[:, :, :, 1]) is False
    assert t.lookup_run([1, 2, 3]) == [(1, 0)]


def test_checksum_travels_down_the_spill(tmp_path):
    disk = DiskOffloadTier(4, SHAPE, np.float32,
                           path=str(tmp_path / "g3.mmap"))
    t = HostOffloadTier(1, SHAPE, np.float32, spill=disk)
    batch = _pages(2, seed=5)
    t.put_batch([1], [0], batch[:, :, :, :1])
    crc = t.checksum_of(1)
    assert crc is not None
    t.put_batch([2], [1], batch[:, :, :, 1:])  # capacity 1: spills 1
    assert 1 in disk
    # G3 inherits G2's seal-time crc (no re-mint over DRAM bytes)
    assert disk.checksum_of(1) == crc
    assert t.checksum_of(1) == crc  # falls through the tier walk
    disk.close()


# ---------------------------------------------------------------------------
# G3 crash consistency: manifest journal + startup scrub


def test_g3_manifest_restart_survival(tmp_path):
    path = str(tmp_path / "g3.mmap")
    disk = DiskOffloadTier(4, SHAPE, np.float32, path=path)
    batch = _pages(3, seed=6)
    disk.put_batch([11, 12, 13], [0, 11, 12], batch)
    crcs = [disk.checksum_of(h) for h in (11, 12, 13)]
    # crash: abandon the tier without close() — the journal was flushed
    # per record, the pool through the OS page cache
    del disk

    disk2 = DiskOffloadTier(4, SHAPE, np.float32, path=path,
                            scrub_on_start=True)
    assert disk2.scrub_recovered == 3 and disk2.scrub_dropped == 0
    assert disk2.lookup_run([11, 12, 13]) == [(11, 0), (12, 11), (13, 12)]
    np.testing.assert_array_equal(disk2.gather([11, 12, 13]), batch)
    assert [disk2.checksum_of(h) for h in (11, 12, 13)] == crcs
    disk2.close()


def test_g3_scrub_drops_torn_and_corrupt_entries(tmp_path):
    path = str(tmp_path / "g3.mmap")
    disk = DiskOffloadTier(4, SHAPE, np.float32, path=path)
    batch = _pages(3, seed=7)
    disk.put_batch([21, 22, 23], [0, 21, 22], batch)
    slot_22 = disk._index[22][0]
    del disk  # crash without close

    # journal damage: a torn tail (partial write) + an out-of-range slot
    with open(path + ".manifest", "a") as f:
        f.write(json.dumps({"put": 99, "parent": 0, "slot": 77,
                            "crc": 1, "scale": None}) + "\n")
        f.write('{"put": 100, "par')  # torn mid-record
    # at-rest rot: flip a byte inside 22's page region
    pool = np.memmap(path, dtype=np.float32, mode="r+",
                     shape=(2, 2, 1, 4, PS, 4))
    pool[0, 0, 0, slot_22, 0, 0] += 1.0
    pool.flush()
    del pool

    disk2 = DiskOffloadTier(4, SHAPE, np.float32, path=path,
                            scrub_on_start=True)
    # 21 and 23 come back; 22 (rotted), 99 (bad slot) and the torn line
    # are dropped as misses — never served
    assert 21 in disk2 and 23 in disk2 and 22 not in disk2
    assert 99 not in disk2
    assert disk2.scrub_recovered == 2 and disk2.scrub_dropped >= 3
    np.testing.assert_array_equal(disk2.read_page(21), batch[:, :, :, 0])
    disk2.close()


def test_g3_truncated_file_extends_and_drops_tail(tmp_path):
    """A file truncated mid-growth (crash) reattaches: sparse-extended
    to full size, entries whose bytes were lost fail crc -> misses."""
    path = str(tmp_path / "g3.mmap")
    disk = DiskOffloadTier(4, SHAPE, np.float32, path=path)
    batch = _pages(4, seed=8)
    disk.put_batch([1, 2, 3, 4], [0, 1, 2, 3], batch)
    nbytes = os.path.getsize(path)
    del disk
    # lose the file's tail: in the pool's C layout that zeroes the last
    # page-slot's final rows (a torn last write), leaving earlier slots
    # byte-complete
    os.truncate(path, nbytes - 100)

    disk2 = DiskOffloadTier(4, SHAPE, np.float32, path=path,
                            scrub_on_start=True)
    assert os.path.getsize(path) == nbytes  # sparse re-extended
    # some slots survived, the zeroed tail was dropped — and nothing
    # that IS served mismatches its crc
    assert 1 <= disk2.scrub_recovered < 4
    for h in (1, 2, 3, 4):
        if h in disk2:
            i = h - 1
            np.testing.assert_array_equal(
                disk2.read_page(h), batch[:, :, :, i]
            )
    disk2.close()


def test_stale_manifest_without_pool_starts_clean(tmp_path):
    path = str(tmp_path / "g3.mmap")
    with open(path + ".manifest", "w") as f:
        f.write(json.dumps({"g3_manifest": 1}) + "\n")
    disk = DiskOffloadTier(4, SHAPE, np.float32, path=path)
    assert len(disk) == 0
    assert not os.path.exists(path + ".manifest")
    disk.close()


# ---------------------------------------------------------------------------
# wire: receiver verify, typed nacks, retry-once, frame hardening


def _mk_pool_server():
    pool = {"data": np.zeros(SHAPE[:3] + (8,) + SHAPE[3:], np.float32)}

    def read_fn(pages):
        return pool["data"][:, :, :, pages]

    def write_fn(pages, data):
        pool["data"][:, :, :, pages] = data

    return pool, BlockTransferServer(read_fn=read_fn, write_fn=write_fn)


async def test_wire_corruption_nacked_then_retried_once():
    pool, srv = _mk_pool_server()
    host, port = await srv.start()
    try:
        payload = _pages(2, seed=9)
        before = KV_INTEGRITY.get("dynamo_kv_integrity_retries_total")
        # one-shot wire corruption: first send nacked (bytes never reach
        # the pool), automatic retry lands clean
        CHAOS.arm("corrupt_frame", once=True)
        await write_remote_pages(host, port, [0, 1], payload)
        np.testing.assert_array_equal(pool["data"][:, :, :, [0, 1]],
                                      payload)
        assert KV_INTEGRITY.get(
            "dynamo_kv_integrity_retries_total"
        ) == before + 1

        # persistent corruption: the retry fails too and the typed error
        # reaches the caller's fallback path — the pool stays clean
        CHAOS.arm("corrupt_frame", probability=1.0)
        with pytest.raises(KvIntegrityError):
            await write_remote_pages(host, port, [2, 3], payload)
        assert not pool["data"][:, :, :, [2, 3]].any()
    finally:
        await srv.stop()


async def test_wire_read_verified_client_side():
    pool, srv = _mk_pool_server()
    host, port = await srv.start()
    try:
        payload = _pages(2, seed=10)
        await write_remote_pages(host, port, [4, 5], payload)
        got = await read_remote_pages(host, port, [4, 5])
        np.testing.assert_array_equal(got, payload)
        # corruption on the read direction is caught by the client
        CHAOS.arm("corrupt_frame", probability=1.0)
        with pytest.raises(KvIntegrityError):
            await read_remote_pages(host, port, [4, 5])
    finally:
        await srv.stop()


async def test_stream_integrity_nack_replays_once():
    pool, srv = _mk_pool_server()
    host, port = await srv.start()
    try:
        payload = _pages(4, seed=11)
        chunks = [([0, 1], payload[:, :, :, :2]),
                  ([2, 3], payload[:, :, :, 2:])]
        before = KV_INTEGRITY.get("dynamo_kv_integrity_retries_total")
        CHAOS.arm("corrupt_frame", once=True)
        # the corrupted chunk is rejected BEFORE its scatter, the eof ack
        # carries the typed nack, and the whole stream replays clean
        assert await write_pages_stream(host, port, chunks) == 2
        np.testing.assert_array_equal(pool["data"][:, :, :, :4], payload)
        assert KV_INTEGRITY.get(
            "dynamo_kv_integrity_retries_total"
        ) == before + 1
    finally:
        await srv.stop()


async def test_malformed_frame_typed_nack_connection_survives():
    """A header whose geometry doesn't match the payload byte count is
    rejected with a typed error frame — not an unhandled ValueError that
    kills the connection: the SAME connection then serves a clean op."""
    pool, srv = _mk_pool_server()
    host, port = await srv.start()
    reader, writer = await asyncio.open_connection(host, port)
    try:
        payload = _pages(1, seed=12)
        raw = np.ascontiguousarray(payload).view(np.uint8).reshape(-1)
        # claim 2 pages but ship 1 page of bytes
        writer.write(encode_frame2(
            {"op": "write_pages", "pages": [0, 1], "dtype": "float32",
             "shape": [2, 2, 1, 2, PS, 4]}, raw.tobytes(),
        ))
        await writer.drain()
        header, _ = await read_frame2(reader)
        assert header.get("ok") is False
        assert header.get("kind") == "frame"
        # connection survived: a well-formed write on the same socket
        writer.write(encode_frame2(
            {"op": "write_pages", "pages": [6], "dtype": "float32",
             "shape": [2, 2, 1, 1, PS, 4]}, raw.tobytes(),
        ))
        await writer.drain()
        header, _ = await read_frame2(reader)
        assert header.get("ok") is True
        np.testing.assert_array_equal(pool["data"][:, :, :, 6],
                                      payload[:, :, :, 0])
    finally:
        writer.close()
        await srv.stop()


# ---------------------------------------------------------------------------
# engine integration: quarantine-and-recompute, token-identical output


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    # SMALL HBM pool (12 usable pages) + host tier: pressure evicts fast
    ecfg = EngineConfig(
        num_pages=13, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", host_offload_pages=16, offload_batch=8,
    )
    params = llama.init_params(cfg, 0)
    return cfg, ecfg, params


def mk_engine(setup, **kw):
    cfg, ecfg, params = setup
    if kw:
        ecfg = replace(ecfg, **kw)
    return TpuEngine(cfg, ecfg, params=params, mesh_config=MeshConfig(tp=1))


async def collect(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def req_for(prompt, n_new=6):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n_new, ignore_eos=True),
    )


async def _evict_to_host(eng, prompt_a):
    """Run prompt_a, then pressure the 12-page HBM pool until its prefix
    blocks live only in the host tiers. Returns A's 3 block hashes."""
    await collect(eng, req_for(prompt_a))
    for _ in range(200):
        spill = getattr(eng.offload, "spill", None)
        if len(eng.offload) + (len(spill) if spill else 0) >= 3:
            break
        await asyncio.sleep(0.02)
    for base in (100, 200, 300, 400):
        await collect(eng, req_for(list(range(base, base + 49))))
        await asyncio.sleep(0.05)
    seq = TokenBlockSequence.from_tokens(prompt_a, PS, salt="")
    hashes = seq.block_hashes()[:3]
    assert eng.allocator.cached_prefix_len(hashes) == 0, \
        "test premise: A's blocks must be evicted from HBM"
    return hashes


async def test_g2_bitflip_quarantined_and_token_identical(setup):
    """The tier-1 chaos smoke: a bit-flip in a G2-resident page is caught
    at onboard admission, the block is quarantined, the affected prefix
    recomputes as prefill — and the stream is token-identical."""
    eng = mk_engine(setup)
    prompt_a = list(range(1, 50))  # 3 complete blocks + tail
    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))
    hashes = await _evict_to_host(eng, prompt_a)
    assert all(h in eng.offload for h in hashes), \
        "test premise: A's blocks must sit in G2"

    # silent DRAM rot: flip one byte of the MIDDLE block's pool bytes
    slot = eng.offload._index[hashes[1]][0]
    eng.offload._pool[:, :, :, slot].view(np.uint8)[0, 0, 0, 0, 1] ^= 1

    before = KV_INTEGRITY.snapshot()
    out_a2 = await collect(eng, req_for(prompt_a))
    assert out_a2 == ref  # corruption costs latency, never wrong tokens
    after = KV_INTEGRITY.snapshot()
    assert after["dynamo_kv_integrity_failed_total"] > \
        before["dynamo_kv_integrity_failed_total"]
    assert after["dynamo_kv_integrity_quarantined_total"] == \
        before["dynamo_kv_integrity_quarantined_total"] + 1
    # block 1 AND everything behind it recomputed (the run is truncated
    # at the first bad block — later blocks hang off a corrupt prefix)
    assert after["dynamo_kv_integrity_recomputed_total"] >= \
        before["dynamo_kv_integrity_recomputed_total"] + 2
    assert hashes[1] in eng.kv_quarantine
    assert hashes[1] not in eng.offload  # dropped from every tier
    await eng.stop()


async def test_chaos_flip_storm_token_identical(setup):
    """flip_kv_bits armed at p=1: EVERY onboard gather is corrupted, so
    every prefix hit degrades to recompute — output still identical."""
    eng = mk_engine(setup)
    prompt_a = list(range(1, 50))
    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))
    await _evict_to_host(eng, prompt_a)
    CHAOS.arm("flip_kv_bits", probability=1.0)
    out = await collect(eng, req_for(prompt_a))
    assert out == ref
    assert CHAOS.points["flip_kv_bits"].injected_total >= 1
    CHAOS.reset()
    # quarantine TTL'd entries flush; a later clean re-send still works
    out2 = await collect(eng, req_for(prompt_a))
    assert out2 == ref
    await eng.stop()


async def test_g3_engine_crash_restart_scrub_token_identical(
    setup, tmp_path
):
    """Acceptance pin: 'kill' the engine mid-life (snapshot the G3 pool +
    journal as they are on disk, no clean close), restart against the
    snapshot with --scrub-on-start: fully-written blocks are recovered
    and served, a torn journal tail is dropped as a miss, and the re-sent
    prompt is token-identical."""
    path = str(tmp_path / "g3.mmap")
    eng = mk_engine(setup, host_offload_pages=2, disk_offload_pages=16,
                    disk_offload_path=path)
    prompt_a = list(range(1, 50))
    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))
    hashes = await _evict_to_host(eng, prompt_a)
    assert sum(h in eng.offload.spill for h in hashes) >= 1, \
        "test premise: G2 pressure must spill A to disk"

    # crash snapshot: the on-disk state at kill time, BEFORE the clean
    # close's compaction — journal puts/drops as they were flushed
    crash_path = str(tmp_path / "g3-crash.mmap")
    shutil.copy(path, crash_path)
    shutil.copy(path + ".manifest", crash_path + ".manifest")
    with open(crash_path + ".manifest", "a") as f:
        f.write('{"put": 424242, "sl')  # torn mid-write record
    await eng.stop()

    before = KV_INTEGRITY.snapshot()
    eng2 = mk_engine(setup, host_offload_pages=2, disk_offload_pages=16,
                     disk_offload_path=crash_path, scrub_on_start=True)
    spill = eng2.offload.spill
    assert spill.scrub_recovered >= 1
    assert spill.scrub_dropped >= 1  # the torn line
    assert 424242 not in spill
    after = KV_INTEGRITY.snapshot()
    assert after["dynamo_kv_integrity_g3_scrub_recovered_total"] > \
        before["dynamo_kv_integrity_g3_scrub_recovered_total"]
    assert after["dynamo_kv_integrity_g3_scrub_dropped_total"] > \
        before["dynamo_kv_integrity_g3_scrub_dropped_total"]

    out = await collect(eng2, req_for(prompt_a))
    assert out == ref
    await eng2.stop()


async def test_g3_truncation_chaos_token_identical(setup, tmp_path):
    """truncate_g3 fired before an onboard gather: blocks in the zeroed
    tail fail admission, quarantine + recompute keep tokens identical."""
    eng = mk_engine(setup, host_offload_pages=2, disk_offload_pages=16,
                    disk_offload_path=str(tmp_path / "g3.mmap"))
    prompt_a = list(range(1, 50))
    ref = await collect(mk_engine(setup, host_offload_pages=0),
                        req_for(prompt_a))
    hashes = await _evict_to_host(eng, prompt_a)
    assert sum(h in eng.offload.spill for h in hashes) >= 1, \
        "test premise: G2 pressure must spill A to disk"
    CHAOS.arm("truncate_g3", once=True)
    out = await collect(eng, req_for(prompt_a))
    assert out == ref
    await eng.stop()


# ---------------------------------------------------------------------------
# config plumbing + offline scrub tool


def test_scrub_on_start_env_plumbing():
    cfg = load_config(env={"DYNTPU_SCRUB_ON_START": "1"})
    assert cfg.scrub_on_start is True
    assert load_config(env={}).scrub_on_start is False
    assert EngineConfig(num_pages=8, page_size=PS).scrub_on_start is False


def _load_scrub_tool():
    spec = importlib.util.spec_from_file_location(
        "scrub_kv", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "scrub_kv.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_scrub_tool_clean_and_corrupt_exit_codes(tmp_path, capsys):
    scrub_kv = _load_scrub_tool()
    path = str(tmp_path / "g3.mmap")
    disk = DiskOffloadTier(4, SHAPE, np.float32, path=path)
    batch = _pages(2, seed=13)
    disk.put_batch([1, 2], [0, 1], batch)
    slot = disk._index[2][0]
    disk.close()

    assert scrub_kv.main([path]) == 0
    report = scrub_kv.scrub(path, path + ".manifest")
    assert report["verified"] == 2 and report["corrupt"] == 0

    pool = np.memmap(path, dtype=np.float32, mode="r+",
                     shape=(2, 2, 1, 4, PS, 4))
    pool[1, 0, 0, slot, 3, 1] += 0.5
    pool.flush()
    del pool
    assert scrub_kv.main([path]) == 1
    report = scrub_kv.scrub(path, path + ".manifest")
    assert report["verified"] == 1 and report["corrupt"] == 1

    assert scrub_kv.main([str(tmp_path / "missing.mmap")]) == 2
