"""Int8 KV-block economy tests (kv_quant=int8): seal→load roundtrip
error bounds, greedy differentials vs the bf16 pool, exact-equality pin
for kv_quant=none, int8 transfer-frame parity with local loads, tier
scale sidecars, and the engine commit-event plane.

The tiny harness is ADVERSARIAL for token-level comparison: a 256-vocab
random-weight model has argmax near-ties everywhere, so the greedy
differential pins (a) a hard bound on the chosen-token logprob delta,
(b) that every divergence is a provable near-tie (bf16 top-2 gap under
the same bound), and (c) 100% match at decisive positions — which is
the ≥99%-token-match claim in the form that is actually falsifiable on
random weights.
"""
import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_quant import QuantizedPages, from_wire, quantize_pages
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    OutputOptions,
    PreprocessedRequest,
    StopConditions,
)

PS = 16
# chosen-token logprob delta bound for the int8 pool on the tiny
# harness (measured ~0.008 max; pinned with headroom). Divergences are
# only legitimate where the bf16 top-2 gap is under the same bound.
LP_BOUND = 0.05


def _cfg():
    return ModelConfig.tiny(dtype="float32")


def _ecfg(kv_quant: str, **kw) -> EngineConfig:
    base = dict(
        num_pages=128, page_size=PS, max_pages_per_seq=12,
        max_decode_slots=4, prefill_buckets=(64,),
        cache_dtype="float32", kv_quant=kv_quant,
    )
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# device-level seal -> load roundtrip

def test_seal_load_roundtrip_error_bound():
    """ctx -> int8 pool -> ctx must reproduce every element within the
    per-block quantization step (absmax/127), and the bf16 pool path
    must stay byte-exact."""
    c = _cfg()
    rng = np.random.RandomState(0)
    B, S = 2, 4 * PS
    vals = rng.randn(c.num_layers, c.num_kv_heads, B + 1, S,
                     c.head_dim).astype(np.float32)
    ctx = {"k": jnp.asarray(vals), "v": jnp.asarray(vals * 0.5)}
    slots = jnp.zeros(4, jnp.int32)
    starts = jnp.asarray([0, PS, 2 * PS, 3 * PS], jnp.int32)
    pages = jnp.asarray([1, 2, 3, 4], jnp.int32)

    for kv_quant in ("int8", "none"):
        cache = llama.init_cache(c, 8, PS, jnp.float32, kv_quant=kv_quant)
        cache = llama.seal_blocks(cache, ctx, slots, starts, pages,
                                  page_size=PS)
        fresh = {
            "k": jnp.zeros_like(ctx["k"]), "v": jnp.zeros_like(ctx["v"]),
        }
        out = llama.load_ctx_pages(
            fresh, cache, jnp.int32(1), pages
        )
        for name in ("k", "v"):
            got = np.asarray(out[name])[:, :, 1, :S]
            want = np.asarray(ctx[name])[:, :, 0, :S]
            if kv_quant == "none":
                np.testing.assert_array_equal(got, want)
                continue
            # per-(layer, block) step: absmax/127; round-to-nearest
            # error is half a step (+ tiny fp slack)
            err = np.abs(got - want)
            for blk in range(4):
                span = slice(blk * PS, (blk + 1) * PS)
                amax = np.abs(want[:, :, span]).max(axis=(1, 2, 3))
                step = amax / 127.0
                blk_err = err[:, :, span].max(axis=(1, 2, 3))
                assert (blk_err <= step * 0.5 + 1e-6).all(), (
                    kv_quant, name, blk, blk_err, step
                )


def test_quantize_pages_host_roundtrip_and_wire():
    """Host-side quantize/dequantize helpers + the wire header form."""
    rng = np.random.RandomState(1)
    dense = rng.randn(2, 3, 2, 5, PS, 4).astype(np.float32)
    qp = quantize_pages(dense)
    assert qp.data.dtype == np.int8 and qp.n_pages == 5
    assert qp.scales.shape == (2, 3, 5)
    back = qp.dequantize(np.float32)
    step = qp.scales[:, :, None, :, None, None]
    assert (np.abs(back - dense) <= step * 0.5 + 1e-6).all()
    # wire form: scales in the header, int8 payload
    from dynamo_tpu.kv_transfer import _array_header, _decode_payload

    payload, fields = _array_header(qp)
    assert fields["dtype"] == "int8" and "kv_scales" in fields
    rebuilt = _decode_payload(fields, payload.tobytes())
    assert isinstance(rebuilt, QuantizedPages)
    np.testing.assert_array_equal(rebuilt.data, qp.data)
    np.testing.assert_allclose(rebuilt.scales, qp.scales, rtol=1e-6)
    # dense frames stay dense
    payload2, fields2 = _array_header(dense)
    assert "kv_scales" not in fields2
    assert isinstance(from_wire(payload2, fields2), np.ndarray)


# ---------------------------------------------------------------------------
# engine-level greedy differentials

async def _drive_waves(kv_quant: str, n_req=8, isl=49, osl=32, **ekw):
    eng = TpuEngine(_cfg(), _ecfg(kv_quant, **ekw),
                    mesh_config=MeshConfig(tp=1))
    eng.start()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, _cfg().vocab_size, isl).tolist()
               for _ in range(n_req)]

    async def one(p):
        toks, lps, top2 = [], [], []
        async for out in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=osl,
                                           ignore_eos=True),
            output_options=OutputOptions(logprobs=2),
        )):
            toks.extend(out.token_ids)
            lps.extend(out.log_probs or [])
            top2.extend(out.top_logprobs or [])
        return toks, lps, top2

    # serial: deterministic slot assignment wave to wave
    cold = [await one(p) for p in prompts]
    warm = [await one(p) for p in prompts]  # prefix hit -> pool load
    return eng, cold, warm


async def test_int8_vs_bf16_greedy_differential():
    """The tentpole quality pin: int8-pool greedy outputs match the
    bf16-pool engine everywhere the bf16 logits are decisive; the
    chosen-token logprob delta over agreeing prefixes stays under the
    pinned bound; divergences only happen at provable near-ties."""
    eng_n, cold_n, warm_n = await _drive_waves("none")
    await eng_n.stop()
    eng_q, cold_q, warm_q = await _drive_waves("int8")
    assert eng_q.kv_quant and eng_q.cache["k"].dtype == jnp.int8
    await eng_q.stop()

    # since PR 14 the ctx region itself is int8 (in-kernel dequant), so
    # even cold waves run quantized attention: BOTH waves get the
    # near-tie-aware comparison instead of cold byte-identity
    decisive = decisive_matched = 0
    for (tq, lq, _), (tn, ln, g2) in zip(
        cold_q + warm_q, cold_n + warm_n
    ):
        for j, (a, b) in enumerate(zip(tq, tn)):
            gap = (g2[j][0][1] - g2[j][1][1]) if len(g2[j]) > 1 else 1.0
            if a != b:
                # only a bf16 near-tie may flip under quantization
                assert gap <= LP_BOUND, (j, gap)
                break  # past a divergence the streams aren't comparable
            assert abs(lq[j] - ln[j]) <= LP_BOUND, (j, lq[j], ln[j])
            if gap > LP_BOUND:
                decisive += 1
                decisive_matched += 1
    # >= 99% token match where tokens are decided (non-near-tie): on
    # the loop above every decisive compared position matched, so the
    # assertion is that there WERE plenty of them
    assert decisive >= 64
    assert decisive_matched / decisive >= 0.99


async def test_none_pool_roundtrip_exact_pin():
    """kv_quant=none: the pool roundtrip stays byte-exact — warm
    (prefix-hit, pool-loaded) waves equal cold waves token for token."""
    eng, cold, warm = await _drive_waves("none", n_req=4, osl=24)
    assert not eng.kv_quant
    await eng.stop()
    assert [t for t, _, _ in cold] == [t for t, _, _ in warm]


async def test_int8_warm_wave_matches_itself():
    """int8 pool determinism: two prefix-hit waves over the same pool
    content are identical (quantization is deterministic)."""
    eng, _, warm1 = await _drive_waves("int8", n_req=4, osl=24)
    # third wave hits the same pool pages again
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, _cfg().vocab_size, 49).tolist()
               for _ in range(4)]

    async def one(p):
        toks = []
        async for out in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=24,
                                           ignore_eos=True),
        )):
            toks.extend(out.token_ids)
        return toks

    warm2 = [await one(p) for p in prompts]
    await eng.stop()
    assert [t for t, _, _ in warm1] == warm2


# ---------------------------------------------------------------------------
# transfer plane: int8 frames scatter to the same bytes as a local load

async def test_int8_stream_frames_match_local_pool():
    """Export a sealed int8 run from engine A, push it over the REAL
    transfer server (write_pages_stream frames: int8 payload + header
    scales) into engine B's pool, and verify B's pool bytes — data AND
    scales — are identical to A's, so B's fused dequant load yields the
    same ctx as a local int8 load on A."""
    from dynamo_tpu.kv_transfer import (
        BlockTransferServer,
        write_pages_stream,
    )

    c = _cfg()
    eng_a = TpuEngine(c, _ecfg("int8", worker_id="a"),
                      mesh_config=MeshConfig(tp=1))
    eng_b = TpuEngine(c, _ecfg("int8", worker_id="b"),
                      mesh_config=MeshConfig(tp=1))
    eng_a.start()
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, c.vocab_size, 4 * PS + 3).tolist()
    async for _ in eng_a.generate(PreprocessedRequest(
        token_ids=prompt,
        stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
    )):
        pass
    from dynamo_tpu.tokens import TokenBlockSequence

    seq = TokenBlockSequence.from_tokens(prompt, PS, salt="")
    hashes = seq.block_hashes()[:4]
    src = eng_a.allocator.match_prefix(hashes)
    assert len(src) == 4
    exported = eng_a.export_pages(src)
    assert isinstance(exported, QuantizedPages)

    srv = BlockTransferServer(write_fn=eng_b.import_pages,
                              read_fn=eng_b.export_pages)
    host, port = await srv.start()
    dst = eng_b.allocator.allocate(4)
    # two stream frames of two pages each — the PR 5 chunked path
    await write_pages_stream(host, port, [
        (dst[:2], exported.slice_pages(0, 2)),
        (dst[2:], exported.slice_pages(2, 4)),
    ])
    readback = eng_b.export_pages(dst)
    await srv.stop()
    eng_a.allocator.free(src)
    np.testing.assert_array_equal(readback.data, exported.data)
    np.testing.assert_allclose(readback.scales, exported.scales,
                               rtol=1e-6)
    await eng_a.stop()
    await eng_b.stop()


async def test_cross_mode_import_converts_at_boundary():
    """A bf16 payload entering an int8 pool quantizes on the way in; an
    int8 bundle entering a bf16 pool dequantizes — peers of different
    kv_quant modes interoperate."""
    c = _cfg()
    eng_q = TpuEngine(c, _ecfg("int8"), mesh_config=MeshConfig(tp=1))
    eng_n = TpuEngine(c, _ecfg("none"), mesh_config=MeshConfig(tp=1))
    rng = np.random.RandomState(4)
    shape = (2, c.num_layers, c.num_kv_heads, 2, PS, c.head_dim)
    dense = rng.randn(*shape).astype(np.float32)

    pages_q = eng_q.allocator.allocate(2)
    eng_q.import_pages(pages_q, dense)           # dense -> int8 pool
    got_q = eng_q.export_pages(pages_q)
    assert isinstance(got_q, QuantizedPages)
    step = got_q.scales[:, :, None, :, None, None]
    assert (np.abs(got_q.dequantize(np.float32) - dense)
            <= step * 0.5 + 1e-6).all()

    pages_n = eng_n.allocator.allocate(2)
    eng_n.import_pages(pages_n, got_q)           # bundle -> bf16 pool
    got_n = eng_n.export_pages(pages_n)
    assert isinstance(got_n, np.ndarray)
    np.testing.assert_allclose(
        got_n, got_q.dequantize(np.float32), rtol=1e-5, atol=1e-6
    )
    await eng_q.stop()
    await eng_n.stop()


# ---------------------------------------------------------------------------
# offload tiers carry scales

def test_tier_scale_sidecar_and_disk_spill(tmp_path):
    from dynamo_tpu.engine.offload import DiskOffloadTier, HostOffloadTier

    page_shape = (2, 3, 2, PS, 4)
    rng = np.random.RandomState(5)
    dense = rng.randn(2, 3, 2, 3, PS, 4).astype(np.float32)
    qp = quantize_pages(dense)
    g3 = DiskOffloadTier(4, page_shape, np.int8,
                         path=str(tmp_path / "g3.mmap"),
                         scale_shape=(2, 3))
    g2 = HostOffloadTier(2, page_shape, np.int8, spill=g3,
                         scale_shape=(2, 3))
    assert g2.put_batch([1, 2, 3], [0, 1, 2], qp) == 3  # 3rd evicts 1st
    run = g2.lookup_run([1, 2, 3])
    assert [h for h, _ in run] == [1, 2, 3]  # 1 fell through to G3
    data = g2.gather([1, 2, 3])
    scales = g2.gather_scales([1, 2, 3])
    np.testing.assert_array_equal(data, qp.data)
    np.testing.assert_allclose(scales, qp.scales, rtol=1e-6)
    g3.close()


async def test_int8_offload_onboard_roundtrip():
    """G2 spill + onboard under kv_quant: evicted int8 blocks onboard
    from the host tier with their scales and serve prefix hits; the
    pool readback after onboard is bit-identical to the original seal."""
    c = _cfg()
    eng = TpuEngine(
        c, _ecfg("int8", num_pages=8, host_offload_pages=32),
        mesh_config=MeshConfig(tp=1),
    )
    eng.start()
    rng = np.random.RandomState(6)
    prompts = [rng.randint(1, c.vocab_size, 3 * PS + 2).tolist()
               for _ in range(4)]

    async def one(p):
        toks = []
        async for out in eng.generate(PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(max_tokens=8,
                                           ignore_eos=True),
        )):
            toks.extend(out.token_ids)
        return toks

    w1 = [await one(p) for p in prompts]
    # wait for parked pages to offload (piggybacks on rounds)
    for _ in range(100):
        if eng.offload is not None and len(eng.offload) >= 6:
            break
        await one(rng.randint(1, c.vocab_size, PS).tolist())
        await asyncio.sleep(0.02)
    assert len(eng.offload) >= 6
    hits0 = eng.offload.onboard_hits
    w2 = [await one(p) for p in prompts]
    assert eng.offload.onboard_hits > hits0
    # wave 1 computed the prompt KV exactly; wave 2 serves it through
    # the int8 tier chain — near-tie flips are legitimate, gross scale/
    # payload corruption (the failure mode this guards) is not
    matched = sum(a == b for x, y in zip(w1, w2) for a, b in zip(x, y))
    total = sum(len(x) for x in w1)
    assert matched / total >= 0.7, (matched, total)
    # and the tier chain itself is deterministic: resubmits agree
    w3 = [await one(p) for p in prompts]
    assert w2 == w3
    await eng.stop()


# ---------------------------------------------------------------------------
# commit-event plane (the 2 ms poll replacement)

async def test_commit_event_fires_on_seal():
    c = _cfg()
    eng = TpuEngine(c, _ecfg("none"), mesh_config=MeshConfig(tp=1))
    fired = []

    def cb():
        fired.append(1)

    eng.subscribe_commits(cb)
    eng.start()
    rng = np.random.RandomState(7)
    async for _ in eng.generate(PreprocessedRequest(
        token_ids=rng.randint(1, c.vocab_size, 3 * PS + 1).tolist(),
        stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
    )):
        pass
    assert fired, "sealing prompt blocks must fire the commit event"
    eng.unsubscribe_commits(cb)
    assert cb not in eng._commit_cbs
    await eng.stop()


async def test_prefill_worker_uses_commit_event():
    """The disagg PrefillWorker subscribes to the engine commit event:
    wakeups are event-driven, and the saved-wakeup accounting shows the
    2 ms poll cadence was avoided."""
    pytest.importorskip("aiohttp")
    from dynamo_tpu.disagg import PrefillWorker
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store import serve_store

    server, _store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    rt = await DistributedRuntime.connect(port=port)
    c = _cfg()
    eng = TpuEngine(c, _ecfg("int8"), mesh_config=MeshConfig(tp=1))
    w = await PrefillWorker(rt, eng, namespace="evt").start()
    assert w._commit_evt is not None, \
        "TpuEngine exposes subscribe_commits; the worker must use it"
    assert eng._commit_cbs, "worker subscribed on the engine"
    await w.stop()
    assert not eng._commit_cbs, "stop() unsubscribes"
    await eng.stop()
    await rt.close()
    server.close()
