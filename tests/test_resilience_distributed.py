"""Resilience plane through the distributed stack: chaos injection on the
remote-engine path, the /drain control, and the mid-stream kill
differential on real TpuEngines (greedy output must be byte-identical to
an uninterrupted run after a migration).
"""
import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.resilience import CHAOS, RESILIENCE
from tests.test_distributed_serving import chat, setup_system, teardown


@pytest.fixture(autouse=True)
def _reset_globals():
    RESILIENCE.reset()
    CHAOS.reset()
    yield
    RESILIENCE.reset()
    CHAOS.reset()


async def _wait_models(manager, n=1):
    for _ in range(200):
        if len(manager) >= n:
            return
        await asyncio.sleep(0.02)
    raise TimeoutError("model discovery timed out")


async def test_chaos_kill_worker_smoke():
    """Tier-1 chaos smoke: arm kill_worker on the worker serving path,
    stream through the full distributed stack, and verify the router
    migrates — the client still gets a complete 200 response and
    dynamo_migration_total increments."""
    server, workers, frontend_rt, watcher, client, manager = (
        await setup_system(2)
    )
    try:
        await _wait_models(manager)
        # clean request first (workers warm, routers built)
        r = await chat(client, "w1 w2 w3 w4 w5", max_tokens=6)
        assert r.status == 200

        CHAOS.arm("kill_worker", after_outputs=2, once=True)
        r = await chat(client, "w1 w2 w3 w4 w5", max_tokens=6)
        assert r.status == 200
        body = await r.json()
        # the stream survived the kill and ran to its finish. (Exact
        # token identity is asserted in the TpuEngine differentials —
        # the mocker's deterministic token function is not
        # continuation-consistent, so counts here are approximate.)
        assert body["choices"][0]["finish_reason"] in ("stop", "length")
        assert body["usage"]["completion_tokens"] >= 4
        assert RESILIENCE.get("dynamo_migration_total") == 1
        assert RESILIENCE.get(
            "dynamo_resilience_chaos_injections_total") == 1
        assert not CHAOS.points["kill_worker"].armed  # once: self-disarmed
    finally:
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_chaos_delay_point_is_benign():
    """delay injections slow streams without failing them."""
    server, workers, frontend_rt, watcher, client, manager = (
        await setup_system(1)
    )
    try:
        await _wait_models(manager)
        CHAOS.arm("delay", delay_s=0.01)
        r = await chat(client, "w1 w2 w3", max_tokens=3)
        assert r.status == 200
        assert CHAOS.points["delay"].injected_total >= 1
        assert RESILIENCE.get("dynamo_migration_total") == 0
    finally:
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_drain_http_control_deregisters_and_finishes():
    """POST /drain on a worker's system server: the worker stops
    admitting, deregisters (discovery drops it), finishes in-flight work
    and reports drained; traffic continues on the survivor."""
    from dynamo_tpu.resilience.drain import DrainController
    from dynamo_tpu.runtime.system_server import SystemServer

    server, workers, frontend_rt, watcher, client, manager = (
        await setup_system(2)
    )
    sys_client = None
    try:
        await _wait_models(manager)
        rt0, eng0, served0 = workers[0]
        drained = asyncio.Event()
        controller = DrainController(
            eng0,
            on_deregister=served0.lease.revoke,
            on_drained=drained.set,
            timeout_s=10.0,
        )
        sysrv = SystemServer(eng0, worker_id=str(served0.lease_id),
                             drain=controller)
        sys_client = TestClient(TestServer(sysrv.app))
        await sys_client.start_server()

        resp = await sys_client.get("/drain")
        assert (await resp.json())["state"] == "serving"
        resp = await sys_client.post("/drain")
        assert resp.status == 200
        assert (await resp.json())["state"] in ("draining", "drained")

        await asyncio.wait_for(drained.wait(), timeout=10.0)
        resp = await sys_client.get("/drain")
        assert (await resp.json())["state"] == "drained"

        # deregistration propagated: the drained worker leaves the
        # frontend's router, and traffic keeps flowing on the survivor
        for _ in range(200):
            push = watcher._routers.get("mock-model")
            if push is not None and len(push.workers) == 1:
                break
            await asyncio.sleep(0.02)
        assert len(watcher._routers["mock-model"].workers) == 1
        for _ in range(3):
            r = await chat(client, "w6 w7 w8")
            assert r.status == 200
        assert RESILIENCE.get("dynamo_resilience_drains_total") == 1
    finally:
        if sys_client is not None:
            await sys_client.close()
        await teardown(server, workers, frontend_rt, watcher, client)


async def test_system_server_chaos_control():
    """tools/chaos.py's wire surface: GET lists points, POST arms,
    DELETE disarms — against a live system server."""
    from dynamo_tpu.runtime.system_server import SystemServer

    sysrv = SystemServer(None, worker_id="w0")
    c = TestClient(TestServer(sysrv.app))
    await c.start_server()
    try:
        resp = await c.get("/chaos")
        names = {p["name"] for p in (await resp.json())["points"]}
        assert names == {"kill_worker", "stall_stream", "drop_response",
                         "delay", "storm", "flip_kv_bits",
                         "corrupt_frame", "truncate_g3",
                         "kill_store", "partition_store"}
        resp = await c.post("/chaos", json={
            "point": "kill_worker", "probability": 0.5,
            "after_outputs": 3, "once": True,
        })
        assert resp.status == 200
        assert CHAOS.points["kill_worker"].armed
        assert CHAOS.points["kill_worker"].after_outputs == 3
        resp = await c.post("/chaos", json={"point": "nope"})
        assert resp.status == 400
        resp = await c.delete("/chaos?point=kill_worker")
        assert resp.status == 200
        assert not CHAOS.points["kill_worker"].armed
        # resilience families render on the worker scrape surface
        resp = await c.get("/metrics")
        text = await resp.text()
        assert "# TYPE dynamo_migration_total counter" in text
        assert "dynamo_resilience_draining" in text
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# TpuEngine mid-stream kill differentials


def _tiny_engine(params, cfg):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.parallel.mesh import MeshConfig

    return TpuEngine(
        cfg,
        EngineConfig(num_pages=64, page_size=16, max_pages_per_seq=8,
                     max_decode_slots=2, prefill_buckets=(32, 64),
                     cache_dtype="float32"),
        params=params, mesh_config=MeshConfig(tp=1),
    )


async def test_tpu_engine_migration_differential_greedy():
    """The acceptance differential on REAL engines: two TpuEngines share
    params behind the KV router; the serving worker dies after 3 tokens;
    the migrated stream is token-identical to an uninterrupted run."""
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )

    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    engines = [_tiny_engine(params, cfg) for _ in range(2)]

    def req():
        rng = np.random.RandomState(4)
        return PreprocessedRequest(
            token_ids=rng.randint(1, 256, 20).tolist(),
            stop_conditions=StopConditions(max_tokens=12, ignore_eos=True),
        )

    # uninterrupted reference on engine 0
    ref = []
    async for out in engines[0].generate(req()):
        ref.extend(out.token_ids)
    assert len(ref) == 12

    killed: set = set()

    class Assassin:
        def __init__(self, inner):
            self.inner = inner

        async def generate(self, r):
            arm = r.request_id not in killed
            n = 0
            async for out in self.inner.generate(r):
                yield out
                n += len(out.token_ids)
                if arm and n >= 3:
                    killed.add(r.request_id)
                    raise ConnectionError("tpu worker died mid-stream")

    router = KvRouter(16, KvRouterConfig(router_temperature=0.0))
    push = KvPushRouter(router, {
        "w0": Assassin(engines[0]), "w1": Assassin(engines[1]),
    })
    try:
        got = []
        async for out in push.generate(req()):
            got.extend(out.token_ids)
        assert got == ref, "migrated TPU stream diverged from clean run"
        assert push.migrations == 1
        assert RESILIENCE.get("dynamo_migration_total") == 1
    finally:
        for e in engines:
            await e.stop()


@pytest.mark.slow
async def test_multi_worker_kill_mid_stream_full_stack():
    """Slow tier: the full distributed stack (store + discovery + remote
    workers + HTTP frontend) with REAL TpuEngines sharing params; chaos
    kills the serving worker mid-stream and the client's streamed text is
    identical to a clean run."""
    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.watcher import ModelEntry, ModelWatcher, register_llm
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store import serve_store

    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)

    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    workers = []
    for i in range(2):
        rt = await DistributedRuntime.connect(port=port)
        eng = _tiny_engine(params, cfg)
        entry = ModelEntry(name="tpu-res", namespace="res",
                           component="backend", block_size=16,
                           router_mode="kv")
        served = await register_llm(rt, eng, entry, lease_ttl_s=0.5)
        workers.append((rt, eng, served))

    frontend_rt = await DistributedRuntime.connect(port=port)
    manager = ModelManager()
    watcher = await ModelWatcher(
        frontend_rt, manager, namespace="res",
        router_config=KvRouterConfig(router_temperature=0.0),
    ).start()
    svc = HttpService(manager)
    client = TestClient(TestServer(svc.app))
    await client.start_server()

    async def completion():
        r = await client.post("/v1/completions", json={
            "model": "tpu-res", "prompt": "w1 w2 w3 w4 w5 w6 w7 w8",
            "max_tokens": 10, "temperature": 0.0,
        })
        assert r.status == 200, await r.text()
        body = await r.json()
        return body["choices"][0]["text"], body["usage"]["completion_tokens"]

    try:
        await _wait_models(manager)
        clean_text, clean_n = await completion()
        assert clean_n == 10

        CHAOS.arm("kill_worker", after_outputs=3, once=True)
        killed_text, killed_n = await completion()
        assert killed_n == 10
        assert killed_text == clean_text, (
            "client-visible stream diverged across the mid-stream kill"
        )
        assert RESILIENCE.get("dynamo_migration_total") == 1
    finally:
        await client.close()
        await watcher.stop()
        await frontend_rt.close()
        for rt, eng, served in workers:
            await served.shutdown()
            await eng.stop()
            await rt.close()
        server.close()
