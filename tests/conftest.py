"""Test configuration: force a virtual 8-device CPU platform before JAX import.

All unit/integration tests run on CPU with 8 virtual devices so sharding
(tp/dp/sp/ep meshes) is exercised without TPU hardware, mirroring the
reference's strategy of testing distributed logic without GPUs
(reference: tests run against mock engines + docker-compose etcd/NATS;
see SURVEY.md §4.7).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DYNAMO_TPU_TEST", "1")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon TPU plugin force-registers itself ("axon,cpu") even when
# JAX_PLATFORMS=cpu is set; override at the config level so tests always run
# on the virtual 8-device CPU platform.
jax.config.update("jax_platforms", "cpu")

# XLA CPU dispatches f32 matmuls to reduced-precision paths by default;
# golden tests against torch need exact f32 accumulation.
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test")
    config.addinivalue_line(
        "markers", "asyncio_timeout(seconds): override the 120s default"
    )
    config.addinivalue_line(
        "markers",
        "slow: needs a real multi-layer model / long wall time — "
        "excluded from the tier-1 run (-m 'not slow')",
    )


def pytest_collection_modifyitems(items):
    for item in items:
        if inspect.iscoroutinefunction(getattr(item, "function", None)):
            item.add_marker(pytest.mark.asyncio)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal asyncio support (pytest-asyncio may not be installed)."""
    fn = pyfuncitem.function
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        marker = pyfuncitem.get_closest_marker("asyncio_timeout")
        timeout = marker.args[0] if marker else 120
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=timeout))
        return True
    return None
