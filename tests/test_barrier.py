"""Leader/worker barrier tests (reference leader_worker_barrier.rs:356
test strategy) + a 2-process jax.distributed CPU smoke test for the
multi-host bootstrap path.
"""
import asyncio
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from dynamo_tpu.runtime.barrier import (
    BarrierAborted,
    BarrierError,
    LeaderBarrier,
    WorkerBarrier,
)
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store


async def start_store():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    return server, server.sockets[0].getsockname()[1]


async def test_barrier_rendezvous():
    server, port = await start_store()
    lkv = await KvClient(port=port).connect()
    wkv1 = await KvClient(port=port).connect()
    wkv2 = await KvClient(port=port).connect()

    leader = LeaderBarrier(lkv, "b1", num_workers=2, timeout_s=5)
    w1 = WorkerBarrier(wkv1, "b1", "n1", timeout_s=5)
    w2 = WorkerBarrier(wkv2, "b1", "n2", timeout_s=5)

    results = await asyncio.gather(
        leader.sync("coordinator=10.0.0.1:1234"),
        w1.sync(),
        w2.sync(),
    )
    assert results[1] == results[2] == "coordinator=10.0.0.1:1234"
    for b in (leader, w1, w2):
        await b.close()
    for kv in (lkv, wkv1, wkv2):
        await kv.close()
    server.close()


async def test_barrier_worker_joins_late():
    """Leader publishes first; a worker arriving later sees the data in
    the snapshot and still completes."""
    server, port = await start_store()
    lkv = await KvClient(port=port).connect()
    wkv = await KvClient(port=port).connect()
    leader = LeaderBarrier(lkv, "b2", num_workers=1, timeout_s=5)
    leader_task = asyncio.create_task(leader.sync("d"))
    await asyncio.sleep(0.3)  # leader is already waiting
    w = WorkerBarrier(wkv, "b2", "n1", timeout_s=5)
    assert await w.sync() == "d"
    await leader_task
    await leader.close()
    await w.close()
    await lkv.close()
    await wkv.close()
    server.close()


async def test_barrier_leader_timeout_aborts_workers():
    server, port = await start_store()
    lkv = await KvClient(port=port).connect()
    wkv = await KvClient(port=port).connect()
    leader = LeaderBarrier(lkv, "b3", num_workers=2, timeout_s=0.4)
    w = WorkerBarrier(wkv, "b3", "n1", timeout_s=5)
    with pytest.raises(BarrierError):
        await asyncio.gather(leader.sync("d"), w.sync())
    # the abort key is visible: a late worker fails fast instead of hanging
    w2kv = await KvClient(port=port).connect()
    w2 = WorkerBarrier(w2kv, "b3", "late", timeout_s=5)
    with pytest.raises(BarrierAborted):
        await w2.sync()
    for kv in (lkv, wkv, w2kv):
        await kv.close()
    server.close()


async def test_barrier_dead_leader_expires():
    """A leader that dies after publishing: its lease-bound data vanishes;
    workers time out rather than waiting forever on stale state."""
    server, port = await start_store()
    lkv = await KvClient(port=port).connect()
    leader = LeaderBarrier(lkv, "b4", num_workers=2, timeout_s=30,
                           lease_ttl_s=0.3)
    leader_task = asyncio.create_task(leader.sync("d"))
    await asyncio.sleep(0.2)
    leader_task.cancel()  # crash the leader mid-wait
    leader.lease._task.cancel()  # stop keepalives -> lease expires
    await asyncio.sleep(1.0)
    kv = await KvClient(port=port).connect()
    assert await kv.get_prefix("dynamo://dynamo/_barrier/b4/") == []
    await kv.close()
    await lkv.close()
    server.close()


# ---------------------------------------------------------------------------
# 2-process jax.distributed CPU smoke (multi-host bootstrap path)

_SMOKE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    coord, rank = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=2, process_id=rank)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()  # 2 procs x 2 cpu
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(jax.devices(), ("dp",))
    x = jax.make_array_from_callback(
        (4,), NamedSharding(mesh, P("dp")),
        lambda idx: jnp.ones((1,), jnp.float32) * (rank + 1),
    )
    total = jax.jit(
        lambda a: jax.numpy.sum(a),
        out_shardings=NamedSharding(mesh, P()),
    )(x)
    print("SMOKE_OK", rank, float(total), flush=True)
""")


def test_jax_distributed_two_process_smoke(tmp_path):
    script = tmp_path / "smoke.py"
    script.write_text(_SMOKE)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(rank)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.skip("jax.distributed CPU smoke timed out on this host")
        outs.append(out)
    if any(p.returncode != 0 for p in procs):
        joined = "\n".join(outs)
        if "distributed" in joined and "not" in joined.lower():
            pytest.skip(f"jax.distributed unsupported here: {joined[-300:]}")
        raise AssertionError(f"smoke failed:\n{joined[-2000:]}")
    # cross-process sum: ranks contribute 1s and 2s over 2 devices each
    assert "SMOKE_OK 0 6.0" in outs[0]
    assert "SMOKE_OK 1 6.0" in outs[1]
