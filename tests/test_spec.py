"""Speculative decoding subsystem (dynamo_tpu/spec/).

The keystone is the differential test: with temperature=0, speculative
decoding — both proposers, several K — must produce token-for-token
identical output to the non-speculative engine, including runs with
mid-batch rejections (KV rollback) and de-speculation at the context
limit, and must leave the prefix-cache block-hash registry in the same
state as a clean run.
"""
import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, WorkerStats
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.sdk import request_stats
from dynamo_tpu.spec.proposer import NGramProposer
from dynamo_tpu.spec.verifier import accept_tokens

PS = 16


# ---------------------------------------------------------------------------
# NGramProposer (pure host)

def test_ngram_proposes_continuation_of_tail_match():
    p = NGramProposer(k=3, max_n=3, min_n=1)
    #          0  1  2  3  4  5  6  7
    history = [5, 6, 7, 8, 9, 1, 6, 7]
    # tail [6, 7] matched at positions 1..2 -> continuation [8, 9, 1]
    assert p.propose(history) == [8, 9, 1]


def test_ngram_prefers_most_recent_match():
    p = NGramProposer(k=2, max_n=2, min_n=1)
    history = [1, 2, 3, 1, 2, 4, 1, 2]
    # [1, 2] occurs at 0 (-> 3) and 3 (-> 4); rightmost wins
    assert p.propose(history) == [4, 1]


def test_ngram_no_match_pads_zeros():
    p = NGramProposer(k=4, max_n=3, min_n=2)
    assert p.propose([1, 2, 3, 4]) == [0, 0, 0, 0]


def test_ngram_short_continuation_padded():
    p = NGramProposer(k=4, max_n=1, min_n=1)
    # tail [2] matches at index 1; the continuation window reaches the
    # end of history ([3, 2]) and pads with zeros
    assert p.propose([1, 2, 3, 2]) == [3, 2, 0, 0]


# ---------------------------------------------------------------------------
# accept_tokens (the on-device acceptance rule, called directly)

def _logits_for(rows, vocab=16):
    """Row i strongly prefers token rows[i]."""
    out = np.full((len(rows), vocab), -5.0, np.float32)
    for i, t in enumerate(rows):
        out[i, t] = 5.0
    return jnp.asarray(out)


def test_accept_greedy_longest_prefix_and_bonus():
    # target argmax chain: 3, 4, 9, 2 ; proposals 3, 4, 7 -> accept 2,
    # bonus = row 2's argmax (9)
    logits = _logits_for([3, 4, 9, 2])
    toks = jnp.asarray([1, 3, 4, 7], jnp.int32)  # pending=1, proposed 3,4,7
    key = jnp.zeros(2, jnp.uint32)
    out, n, _ = accept_tokens(
        logits, toks, key, jnp.float32(0.0), jnp.int32(0),
        jnp.float32(1.0), max_top_k=8,
    )
    assert int(n) == 3
    assert np.asarray(out)[:3].tolist() == [3, 4, 9]


def test_accept_greedy_all_accepted_gets_bonus_row_k():
    logits = _logits_for([3, 4, 9, 2])
    toks = jnp.asarray([1, 3, 4, 9], jnp.int32)
    out, n, _ = accept_tokens(
        logits, toks, jnp.zeros(2, jnp.uint32), jnp.float32(0.0),
        jnp.int32(0), jnp.float32(1.0), max_top_k=8,
    )
    assert int(n) == 4
    assert np.asarray(out).tolist() == [3, 4, 9, 2]


def test_accept_greedy_full_rejection_corrects_first_token():
    logits = _logits_for([3, 4, 9, 2])
    toks = jnp.asarray([1, 8, 8, 8], jnp.int32)
    out, n, _ = accept_tokens(
        logits, toks, jnp.zeros(2, jnp.uint32), jnp.float32(0.0),
        jnp.int32(0), jnp.float32(1.0), max_top_k=8,
    )
    assert int(n) == 1
    assert int(np.asarray(out)[0]) == 3


def test_accept_sampled_certain_proposal_always_accepted():
    # one token holds ~all mass: rejection sampling must accept it and
    # the bonus resample must also produce it
    logits = jnp.asarray(np.where(
        np.arange(16) == 7, 50.0, -50.0
    )[None].repeat(4, 0).astype(np.float32))
    toks = jnp.asarray([1, 7, 7, 7], jnp.int32)
    out, n, _ = accept_tokens(
        logits, toks, jnp.asarray([3, 9], jnp.uint32), jnp.float32(1.0),
        jnp.int32(0), jnp.float32(1.0), max_top_k=8,
    )
    assert int(n) == 4
    assert np.asarray(out).tolist() == [7, 7, 7, 7]


def test_accept_sampled_impossible_proposal_rejected_with_leftover():
    # proposal has ~zero mass -> always rejected; the leftover resample
    # (proposal masked) must return the dominant token
    logits = jnp.asarray(np.where(
        np.arange(16) == 5, 50.0, -50.0
    )[None].repeat(4, 0).astype(np.float32))
    toks = jnp.asarray([1, 9, 9, 9], jnp.int32)
    out, n, _ = accept_tokens(
        logits, toks, jnp.asarray([3, 9], jnp.uint32), jnp.float32(1.0),
        jnp.int32(0), jnp.float32(1.0), max_top_k=8,
    )
    assert int(n) == 1
    assert int(np.asarray(out)[0]) == 5


# ---------------------------------------------------------------------------
# Engine integration

@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    return cfg, params


def make_engine(setup, *, draft=False, **kw):
    cfg, params = setup
    base = dict(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32",
    )
    base.update(kw)
    ekw = {}
    if draft:
        # draft == target: proposals match the target argmax, acceptance
        # should be (near-)total
        ekw = dict(draft_config=cfg, draft_params=params)
    return TpuEngine(
        cfg, EngineConfig(**base), params=params,
        mesh_config=MeshConfig(tp=1), **ekw,
    )


def _prompts(vocab=256):
    rng = np.random.RandomState(0)
    pat = rng.randint(1, vocab, 8).tolist()
    return [pat * 4, rng.randint(1, vocab, 20).tolist()]


async def drive(eng, prompts, max_tokens=24, so=None):
    async def one(p):
        toks, outs = [], []
        req = PreprocessedRequest(
            token_ids=list(p),
            stop_conditions=StopConditions(
                max_tokens=max_tokens, ignore_eos=True
            ),
        )
        if so is not None:
            req.sampling_options = so
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
            outs.append(out)
        return toks, outs
    return await asyncio.gather(*[one(p) for p in prompts])


async def run_engine(setup, prompts, max_tokens=24, so=None, draft=False,
                     **kw):
    eng = make_engine(setup, draft=draft, **kw)
    eng.start()
    try:
        res = await drive(eng, prompts, max_tokens, so)
        stats = eng.spec.stats() if eng.spec else None
        hashes = frozenset(eng.allocator._registry)
        return res, stats, hashes
    finally:
        await eng.stop()


async def test_spec_greedy_differential_ngram():
    """Greedy n-gram speculation is token-identical to the baseline for
    K in {2, 4, 8}, with mid-batch rejections exercised, and leaves the
    prefix-cache hash registry identical to a clean run."""
    setup = (ModelConfig.tiny(dtype="float32"), None)
    setup = (setup[0], llama.init_params(setup[0], 0))
    prompts = _prompts()
    ref, _, ref_hashes = await run_engine(setup, prompts)
    for k in (2, 4, 8):
        spec, st, hashes = await run_engine(
            setup, prompts, speculative="ngram", num_speculative_tokens=k,
        )
        for (rt, _), (stk, _) in zip(ref, spec):
            assert rt == stk, f"K={k}: speculative output diverged"
        assert st["spec_verify_steps"] > 0
        # random-weight targets reject n-gram drafts constantly: the
        # KV-rollback path is genuinely exercised
        assert st["spec_reject_events"] > 0
        # KV consistency: the same blocks sealed under the same chained
        # hashes as the clean run, despite rejected optimistic writes
        assert hashes == ref_hashes


async def test_spec_greedy_differential_draft():
    """Draft-model speculation (draft == target here) is token-identical
    to the baseline and accepts (nearly) everything."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    prompts = _prompts()
    ref, _, ref_hashes = await run_engine(setup, prompts)
    for k in (2, 4, 8):
        spec, st, hashes = await run_engine(
            setup, prompts, draft=True,
            speculative="draft", num_speculative_tokens=k,
        )
        for (rt, _), (stk, _) in zip(ref, spec):
            assert rt == stk, f"K={k}: draft speculative output diverged"
        assert st["spec_acceptance_rate"] > 0.8
        assert hashes == ref_hashes


async def test_spec_despec_at_context_limit():
    """Near the region limit the verify no longer fits: the slot is
    handed back to the fused decode round and the tail continues
    token-identically to the baseline."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 256, 20).tolist()]
    # max_context = 4 * PS = 64 -> cap of 44 new tokens
    ref, _, _ = await run_engine(
        setup, prompts, max_tokens=100, max_pages_per_seq=4,
    )
    for mode, draft in (("ngram", False), ("draft", True)):
        spec, st, _ = await run_engine(
            setup, prompts, max_tokens=100, max_pages_per_seq=4,
            speculative=mode, num_speculative_tokens=4, draft=draft,
        )
        assert ref[0][0] == spec[0][0], f"{mode} tail diverged"
        assert len(spec[0][0]) == 44
        assert st["spec_despec_total"] >= 1


async def test_spec_seeded_temperature_reproducible():
    """temperature>0 speculation consumes the per-slot PRNG stream:
    seeded requests reproduce across runs."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    prompts = _prompts()[:1]
    so = SamplingOptions(temperature=0.9, seed=7)
    a, _, _ = await run_engine(
        setup, prompts, so=so, speculative="ngram",
        num_speculative_tokens=4,
    )
    b, _, _ = await run_engine(
        setup, prompts, so=so, speculative="ngram",
        num_speculative_tokens=4,
    )
    assert a[0][0] == b[0][0]
    assert len(a[0][0]) == 24


def test_accept_penalized_zero_counts_matches_plain():
    """With a zero histogram and identity penalties, the scan variant is
    draw-for-draw identical to the vectorized path (same PRNG key
    consumption) — penalty-free slots co-resident in a penalized round
    produce the same tokens either way."""
    from dynamo_tpu.spec.verifier import accept_tokens_penalized

    rng = np.random.RandomState(5)
    logits = jnp.asarray(rng.randn(5, 16).astype(np.float32))
    toks = jnp.asarray([1, 3, 4, 7, 9], jnp.int32)
    key = jnp.asarray([7, 11], jnp.uint32)
    for temp in (0.0, 0.9):
        a = accept_tokens(
            logits, toks, key, jnp.float32(temp), jnp.int32(0),
            jnp.float32(1.0), max_top_k=8,
        )
        b = accept_tokens_penalized(
            logits, toks, key, jnp.float32(temp), jnp.int32(0),
            jnp.float32(1.0), jnp.zeros(16, jnp.int32),
            jnp.float32(0.0), jnp.float32(0.0), jnp.float32(1.0),
            max_top_k=8,
        )
        for x, y in zip(a, b):
            assert np.asarray(x).tolist() == np.asarray(y).tolist(), temp


async def test_spec_penalized_greedy_differential():
    """Satellite (ROADMAP open item): penalized requests SPECULATE — the
    counts histogram advances inside the accept loop, and greedy output
    under frequency/presence/repetition penalties is token-identical to
    the non-speculative engine."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    prompts = _prompts()
    so = SamplingOptions(
        repetition_penalty=1.3, frequency_penalty=0.4,
        presence_penalty=0.2,
    )
    ref, _, ref_hashes = await run_engine(setup, prompts, so=so)
    for mode, draft in (("ngram", False), ("draft", True)):
        spec, st, hashes = await run_engine(
            setup, prompts, so=so, draft=draft,
            speculative=mode, num_speculative_tokens=4,
        )
        for (rt, _), (stk, _) in zip(ref, spec):
            assert rt == stk, f"{mode}: penalized speculation diverged"
        # the penalized slots really speculated (old behavior parked
        # them on the fused round and verify never ran)
        assert st["spec_verify_steps"] > 0
        assert hashes == ref_hashes


async def test_spec_penalized_seeded_temperature_reproducible():
    """Seeded temperature>0 sampling with penalties reproduces across
    speculative runs (the penalized accept path consumes the same
    per-slot PRNG stream)."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    prompts = _prompts()[:1]
    so = SamplingOptions(temperature=0.9, seed=11, presence_penalty=0.5)
    a, sa, _ = await run_engine(
        setup, prompts, so=so, speculative="ngram",
        num_speculative_tokens=4,
    )
    b, _, _ = await run_engine(
        setup, prompts, so=so, speculative="ngram",
        num_speculative_tokens=4,
    )
    assert a[0][0] == b[0][0]
    assert len(a[0][0]) == 24
    assert sa["spec_verify_steps"] > 0


async def test_spec_penalized_despec_restores_counts():
    """Despeculation hands the penalty HISTOGRAM back to the fused
    sampler: the tail after a context-limit despec stays token-identical
    under penalties (a reset histogram would change the penalty terms
    and fork the stream)."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 256, 20).tolist()]
    so = SamplingOptions(repetition_penalty=1.4, frequency_penalty=0.3)
    ref, _, _ = await run_engine(
        setup, prompts, max_tokens=100, max_pages_per_seq=4, so=so,
    )
    spec, st, _ = await run_engine(
        setup, prompts, max_tokens=100, max_pages_per_seq=4, so=so,
        speculative="ngram", num_speculative_tokens=4,
    )
    assert ref[0][0] == spec[0][0], "penalized despec tail diverged"
    assert len(spec[0][0]) == 44
    assert st["spec_despec_total"] >= 1


async def test_spec_ineligible_requests_take_fused_round():
    """A logprobs request decodes on the normal path (it needs the lp
    step variant) while an eligible one speculates — mixed rounds
    coexist in one engine. Penalized requests are NOT ineligible anymore:
    the verifier's histogram-advancing accept path carries them."""
    from dynamo_tpu.protocols.common import OutputOptions

    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    eng = make_engine(setup, speculative="ngram", num_speculative_tokens=4)
    eng.start()
    try:
        rng = np.random.RandomState(3)
        reqs = []
        for lp in (2, None):
            req = PreprocessedRequest(
                token_ids=rng.randint(1, 256, 12).tolist(),
                stop_conditions=StopConditions(
                    max_tokens=16, ignore_eos=True
                ),
            )
            if lp is not None:
                req.output_options = OutputOptions(logprobs=lp)
            reqs.append(req)

        async def one(req):
            toks = []
            async for out in eng.generate(req):
                toks.extend(out.token_ids)
            return toks
        got = await asyncio.gather(*[one(r) for r in reqs])
        assert all(len(t) == 16 for t in got)
        # the eligible request speculated; the logprobs one did not
        assert eng.spec.verify_steps > 0
        assert eng.step_count > 0  # fused rounds ran for the other slot
    finally:
        await eng.stop()


async def test_spec_metrics_and_sdk_request_stats():
    """Acceptance counters flow end-to-end: engine.metrics() ->
    exporter/system-server gauges, and per-request annotations ->
    sdk.request_stats."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    eng = make_engine(setup, draft=True, speculative="draft",
                      num_speculative_tokens=4)
    eng.start()
    try:
        res = await drive(eng, _prompts()[:1], max_tokens=16)
        m = eng.metrics()
        assert m.worker_stats.spec_proposed_total > 0
        assert m.worker_stats.spec_accepted_total > 0
        assert m.worker_stats.spec_acceptance_rate > 0.5
        st = request_stats(res[0][1])
        assert st.output_tokens == 16
        assert st.spec_proposed > 0
        assert st.spec_acceptance_rate is not None
        assert st.finish_reason == "length"
    finally:
        await eng.stop()
    # exporter rendering (no live control plane needed: feed the
    # aggregator directly)
    from dynamo_tpu.metrics_exporter import MetricsExporter

    exp = MetricsExporter(kv=None)
    exp.aggregator.update(m)
    text = exp.render()
    assert "dynamo_spec_proposed_total" in text
    assert "dynamo_spec_acceptance_rate" in text
    # system server renders the same gauges from a live engine handle
    from dynamo_tpu.runtime.system_server import SystemServer

    class _Stub:
        def metrics(self):
            return m
    assert "dynamo_spec_accepted_total" in SystemServer(_Stub()).render()


async def test_spec_repetitive_prompts_exceed_one_token_per_step():
    """The bench claim at test scale: on repetitive prompts, n-gram
    speculation emits strictly more than one token per verify step."""
    cfg = ModelConfig.tiny(dtype="float32")
    setup = (cfg, llama.init_params(cfg, 0))
    rng = np.random.RandomState(5)
    pat = rng.randint(1, 256, 6).tolist()
    prompts = [pat * 5, (pat[::-1]) * 5]
    _, st, _ = await run_engine(
        setup, prompts, max_tokens=32,
        speculative="ngram", num_speculative_tokens=4,
    )
    steps = st["spec_verify_steps"]
    emitted_per_step = (st["spec_accepted_total"] + steps) / steps
    assert emitted_per_step > 1.0


def test_worker_stats_wire_compat():
    """Old payloads without spec fields still deserialize (defaults)."""
    m = ForwardPassMetrics.from_dict({
        "worker_id": "w0",
        "worker_stats": {"request_active_slots": 1},
        "kv_stats": {},
    })
    assert m.worker_stats.spec_proposed_total == 0
    assert WorkerStats(spec_proposed_total=3).spec_proposed_total == 3


# ---------------------------------------------------------------------------
# tier-2: real multi-layer model shapes (excluded from the tier-1 run)

@pytest.mark.slow
@pytest.mark.asyncio_timeout(600)
async def test_spec_differential_multilayer_model():
    """Same differential guarantee on a deeper/wider model — closer to
    real serving shapes than the 4-layer tiny config."""
    cfg = ModelConfig.tiny(
        dtype="float32", num_layers=8, hidden_size=128,
        intermediate_size=256, vocab_size=512,
    )
    setup = (cfg, llama.init_params(cfg, 0))
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 512, 24).tolist()]
    ref, _, _ = await run_engine(setup, prompts, max_tokens=32)
    spec, st, _ = await run_engine(
        setup, prompts, max_tokens=32,
        speculative="ngram", num_speculative_tokens=4,
    )
    assert ref[0][0] == spec[0][0]
    assert st["spec_verify_steps"] > 0
