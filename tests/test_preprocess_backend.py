"""Preprocessor + backend tests using the offline test tokenizer."""
import pytest

from dynamo_tpu.backend import Backend, StopJail
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    StopConditions,
)
from dynamo_tpu.protocols.openai import ChatCompletionRequest, CompletionRequest
from dynamo_tpu.tokenizer import DecodeStream, make_test_tokenizer


@pytest.fixture
def tok():
    return make_test_tokenizer([f"w{i}" for i in range(50)] + ["hello", "world", "STOP"])


def test_preprocess_chat_renders_template_and_tokenizes(tok):
    pre = OpenAIPreprocessor(tokenizer=tok, model_name="test")
    req = ChatCompletionRequest(
        model="test",
        messages=[{"role": "user", "content": "hello world"}],
        max_tokens=4,
    )
    out = pre.preprocess_chat(req)
    assert out.token_ids  # template rendered then tokenized
    assert out.stop_conditions.max_tokens == 4
    assert set(tok.eos_token_ids) <= set(out.stop_conditions.stop_token_ids)


def test_preprocess_custom_template(tok):
    fmt = PromptFormatter(template="{% for m in messages %}{{ m.content }} {% endfor %}")
    pre = OpenAIPreprocessor(tokenizer=tok, formatter=fmt)
    req = ChatCompletionRequest(
        model="t", messages=[{"role": "user", "content": "hello world"}]
    )
    out = pre.preprocess_chat(req)
    assert out.token_ids == tok.encode("hello world")


def test_preprocess_completion_token_ids(tok):
    pre = OpenAIPreprocessor(tokenizer=tok)
    out = pre.preprocess_completion(CompletionRequest(model="m", prompt=[5, 6, 7]))
    assert out.token_ids == [5, 6, 7]


def test_context_length_enforced(tok):
    pre = OpenAIPreprocessor(tokenizer=tok, context_length=2)
    with pytest.raises(ValueError, match="context length"):
        pre.preprocess_completion(CompletionRequest(model="m", prompt=[1, 2, 3]))


def test_multimodal_content_flattened(tok):
    pre = OpenAIPreprocessor(tokenizer=tok)
    req = ChatCompletionRequest(
        model="t",
        messages=[
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": "hello"},
                    {"type": "image_url", "image_url": {"url": "x"}},
                    {"type": "text", "text": " world"},
                ],
            }
        ],
    )
    out = pre.preprocess_chat(req)
    assert out.token_ids


def test_stop_jail_partial_and_full():
    j = StopJail(["<END>"])
    out, stopped = j.push("hello <E")
    assert out == "hello " and not stopped  # "<E" jailed
    out, stopped = j.push("ND> trailing")
    assert out == "" and stopped  # stop completed; nothing after it leaks
    j2 = StopJail(["<END>"])
    out, stopped = j2.push("a <Eb")
    assert out == "a <Eb" and not stopped  # diverged -> released


def test_decode_stream_incremental(tok):
    ids = tok.encode("hello world w1 w2")
    ds = DecodeStream(tok, prompt_ids=ids[:2])
    text = "".join(ds.step(t) for t in ids[2:])
    assert text == " w1 w2"


async def collect(agen):
    return [x async for x in agen]


async def engine_stream(token_lists, finish=None):
    for i, toks in enumerate(token_lists):
        last = i == len(token_lists) - 1
        yield LLMEngineOutput(token_ids=toks, finish_reason=finish if last else None)


async def test_backend_eos_token(tok):
    b = Backend(tok)
    ids = tok.encode("hello world")
    stream = engine_stream([[ids[0]], [ids[1]], [2]])  # 2 = </s>
    outs = await collect(
        b.transform(stream, prompt_ids=[], stop=StopConditions(stop_token_ids=[2]))
    )
    assert outs[-1].finish_reason == FinishReason.EOS
    text = "".join(o.text or "" for o in outs)
    assert "hello" in text and "world" in text


async def test_backend_max_tokens(tok):
    b = Backend(tok)
    ids = [tok.encode("w1")[0]] * 10
    stream = engine_stream([[i] for i in ids])
    outs = await collect(
        b.transform(stream, prompt_ids=[], stop=StopConditions(max_tokens=3))
    )
    assert outs[-1].finish_reason == FinishReason.LENGTH
    assert sum(len(o.token_ids) for o in outs) == 3


async def test_backend_stop_string(tok):
    b = Backend(tok)
    w = {t: tok.encode(t)[0] for t in ["hello", "STOP", "world"]}
    stream = engine_stream([[w["hello"]], [w["STOP"]], [w["world"]]])
    outs = await collect(
        b.transform(stream, prompt_ids=[], stop=StopConditions(stop=["STOP"]))
    )
    assert outs[-1].finish_reason == FinishReason.STOP
    text = "".join(o.text or "" for o in outs)
    assert "world" not in text and "STOP" not in text


async def test_backend_ignore_eos(tok):
    b = Backend(tok)
    stream = engine_stream([[2], [tok.encode("w1")[0]]], finish=FinishReason.LENGTH)
    outs = await collect(
        b.transform(
            stream,
            prompt_ids=[],
            stop=StopConditions(stop_token_ids=[2], ignore_eos=True),
        )
    )
    assert outs[-1].finish_reason == FinishReason.LENGTH
