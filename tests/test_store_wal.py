"""Store WAL durability tests (PR 15 tentpole, layer 1).

The journal is an append-only JSONL of state mutations (puts, deletes,
lease grants/revokes, queue push/pop) compacted with the same
tmp+fsync+rename discipline as the G3 manifest. A restarted store must
serve IDENTICAL get_prefix/qpop answers — the differential pins below —
and replayed leases get a post-restart grace window so workers can
reclaim their registrations before the sweeper runs.
"""
import json

from dynamo_tpu.runtime.store import KvStore


def _reopen(path, **kw):
    return KvStore(journal_path=str(path), **kw)


# ---------------------------------------------------------------------------
# replay differential: keys + queues


def test_wal_replay_serves_identical_keys_and_queues(tmp_path):
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    s1.put("a/1", "x")
    s1.put("a/2", "y")
    s1.put("b/1", "z")
    s1.delete("b/1")
    for i in range(5):
        s1.qpush("q", f"item-{i}")
    assert s1.qpop("q") == "item-0"  # journaled pop: not replayed twice
    want_keys = [(k, v) for k, v, _ in s1.get_prefix("")]
    s1.close_journal()

    s2 = _reopen(jp)
    assert [(k, v) for k, v, _ in s2.get_prefix("")] == want_keys
    # FIFO order survives: exactly item-1..item-4 remain, in order
    assert [s2.qpop("q") for _ in range(4)] == [
        f"item-{i}" for i in range(1, 5)]
    assert s2.qpop("q") is None
    assert s2.replayed_keys == 2
    assert s2.replayed_queue_items == 4
    assert s2.torn_records == 0


def test_wal_replay_lease_bound_keys_and_revokes(tmp_path):
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    keep = s1.lease_grant(ttl=5.0)
    gone = s1.lease_grant(ttl=5.0)
    s1.put("w/keep", "a", lease=keep)
    s1.put("w/gone", "b", lease=gone)
    s1.lease_revoke(gone)  # revoke deletes the bound key — and is journaled
    s1.close_journal()

    s2 = _reopen(jp)
    assert s2.get("w/keep") is not None
    assert s2.get("w/gone") is None
    # lease ids continue past the replayed max: no id reuse after restart
    assert s2.lease_grant(ttl=1.0) > keep


# ---------------------------------------------------------------------------
# lease grace window after restart


def test_wal_replay_key_rebound_across_leases(tmp_path):
    """Replay regression: `put K lease A; put K lease B; lease_revoke A`
    in the log must not delete K — replay mirrors live put()'s old-lease
    bookkeeping, so the old lease's revoke only sweeps keys it still
    owns."""
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    a = s1.lease_grant(ttl=30.0)
    b = s1.lease_grant(ttl=30.0)
    s1.put("w/k", "v1", lease=a)
    s1.put("w/k", "v2", lease=b)  # rebound: A no longer owns w/k
    s1.lease_revoke(a)
    assert s1.get("w/k") == ("v2", b)
    s1.close_journal()

    s2 = _reopen(jp)
    assert s2.get("w/k") == ("v2", b)  # live/replay differential


def test_wal_replay_rebound_key_survives_post_restart_revoke(tmp_path):
    """Same rebind, but the old lease dies AFTER the restart: the
    replayed _lease_keys set for the old lease must not still claim the
    key, or its expiry/revoke silently drops a live registration."""
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    a = s1.lease_grant(ttl=30.0)
    b = s1.lease_grant(ttl=30.0)
    s1.put("w/k", "v1", lease=a)
    s1.put("w/k", "v2", lease=b)
    s1.put("w/free", "u", lease=a)
    s1.put("w/free", "u")  # rebound to no lease at all
    s1.close_journal()

    s2 = _reopen(jp)
    s2.lease_revoke(a)
    assert s2.get("w/k") == ("v2", b)
    assert s2.get("w/free") == ("u", 0)
    s2.lease_revoke(b)
    assert s2.get("w/k") is None  # the b-binding is still real


def test_wal_replay_restores_revision(tmp_path):
    """Revision must not move backwards across a bounce: deletes and
    overwrites bump it live, so key-count alone undercounts. Per-record
    `rev` fields restore it on replay; compaction folds the records away
    but carries the revision on the meta line."""
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    s1.put("a/1", "x")
    s1.put("a/1", "y")   # overwrite: rev 2, still one live key
    s1.put("a/2", "z")
    s1.delete("a/2")     # delete: rev 4
    want = s1.revision
    assert want == 4
    s1.close_journal()

    s2 = _reopen(jp)    # replays per-record revs, then compacts
    assert s2.revision == want
    s2.close_journal()

    s3 = _reopen(jp)    # compacted journal: meta line carries the rev
    assert s3.revision == want
    assert s3.put("a/3", "w") == want + 1  # keeps counting forward


def test_wal_replay_grants_lease_grace(tmp_path):
    jp = tmp_path / "store.wal"
    now = [0.0]
    s1 = KvStore(clock=lambda: now[0], journal_path=str(jp))
    lease = s1.lease_grant(ttl=0.5)
    s1.put("w/1", "alive", lease=lease)
    s1.close_journal()

    # restart long after the original TTL would have expired: the grace
    # window (not the stale deadline) governs, so the worker has time to
    # reconnect and reclaim before the sweeper evicts it
    now[0] = 100.0
    s2 = KvStore(clock=lambda: now[0], journal_path=str(jp),
                 lease_grace_s=10.0)
    assert s2.get("w/1") is not None
    now[0] = 105.0
    assert s2.sweep_leases() == []
    assert s2.lease_keepalive(lease)  # reclaim refreshes to now + ttl...
    now[0] = 105.4
    assert s2.sweep_leases() == []
    now[0] = 120.0  # ...so unclaimed grace does eventually expire
    assert s2.sweep_leases() == [lease]
    assert s2.get("w/1") is None


# ---------------------------------------------------------------------------
# torn tail


def test_wal_torn_tail_is_skipped_not_fatal(tmp_path):
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    s1.put("a/1", "x")
    s1.put("a/2", "y")
    s1.close_journal()
    # a crash mid-write leaves a torn final record
    with open(jp, "a", encoding="utf-8") as f:
        f.write('{"op":"put","key":"a/3","val')

    s2 = _reopen(jp)
    assert s2.torn_records == 1
    assert [k for k, _, _ in s2.get_prefix("a/")] == ["a/1", "a/2"]
    # the reopened journal keeps accepting writes after the torn tail
    s2.put("a/4", "w")
    s2.close_journal()
    s3 = _reopen(jp)
    assert [k for k, _, _ in s3.get_prefix("a/")] == ["a/1", "a/2", "a/4"]


# ---------------------------------------------------------------------------
# compaction


def test_wal_compaction_bounds_journal_size(tmp_path):
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    for i in range(2000):
        s1.put("hot/key", f"v{i}")
    s1.close_journal()
    # one live key: the journal must have folded the churn away instead
    # of keeping 2000 dead put records
    lines = jp.read_text(encoding="utf-8").splitlines()
    assert len(lines) < 600, f"journal never compacted: {len(lines)} lines"
    assert json.loads(lines[0])["dcp_wal"] == 1
    s2 = _reopen(jp)
    assert s2.get("hot/key") == ("v1999", s2.revision - 1) or \
        s2.get("hot/key")[0] == "v1999"


def test_wal_compaction_writes_grants_before_puts(tmp_path):
    """Replay applies records in order — a lease-bound put must find its
    lease already granted, whatever order the live store created them."""
    jp = tmp_path / "store.wal"
    s1 = _reopen(jp)
    lease = s1.lease_grant(ttl=30.0)
    s1.put("w/1", "v", lease=lease)
    s1.compact_journal()
    s1.close_journal()
    ops = [json.loads(line).get("op")
           for line in jp.read_text(encoding="utf-8").splitlines()[1:]]
    assert ops.index("lease_grant") < ops.index("put")
    s2 = _reopen(jp)
    assert s2.get("w/1") is not None
    s2.lease_revoke(lease)
    assert s2.get("w/1") is None  # the replayed binding is real


# ---------------------------------------------------------------------------
# satellite: expired-but-unswept leases are authoritative inline


def test_put_on_expired_lease_rejected_before_sweep():
    """The sweep cadence must not open a race window: a put (or
    keepalive) against a lease past its deadline is rejected inline even
    if the sweeper has not run yet."""
    now = [0.0]
    s = KvStore(clock=lambda: now[0])
    lease = s.lease_grant(ttl=1.0)
    s.put("w/1", "v", lease=lease)
    now[0] = 1.5  # past the deadline; sweeper has NOT run
    try:
        s.put("w/2", "v", lease=lease)
        raise AssertionError("put on expired lease must raise")
    except KeyError:
        pass
    assert not s.lease_keepalive(lease)
    # the inline check also expired the lease for real: keys are gone
    assert s.get("w/1") is None
