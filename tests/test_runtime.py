"""Distributed runtime tests (reference lib/runtime tests: pipeline.rs,
namespace_etcd_path.rs, leader_worker_barrier.rs test strategy).

Covers the store core (keys/leases/watches/pubsub), the TCP server+client,
the endpoint data plane, and the keystone failover scenario: two workers
register, one dies, traffic fails over to the survivor.
"""
import asyncio
import json

import pytest

from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.endpoint import (
    EndpointServer,
    EndpointStreamError,
    call_endpoint,
)
from dynamo_tpu.runtime.store import KvStore, serve_store


# ---------------------------------------------------------------------------
# store core (no sockets)


def test_store_kv_and_prefix():
    s = KvStore()
    s.put("a/1", "x")
    s.put("a/2", "y")
    s.put("b/1", "z")
    assert s.get("a/1") == ("x", 0)
    assert [k for k, _, _ in s.get_prefix("a/")] == ["a/1", "a/2"]
    assert s.delete("a/1") == 1
    assert s.delete("a/1") == 0
    assert s.delete_prefix("a/") == 1
    assert s.get_prefix("a/") == []


def test_store_lease_expiry_deletes_keys():
    now = [0.0]
    s = KvStore(clock=lambda: now[0])
    lease = s.lease_grant(ttl=5.0)
    s.put("w/1", "alive", lease=lease)
    events = []
    s.watch("w/", events.append)
    now[0] = 4.0
    assert s.sweep_leases() == []
    assert s.lease_keepalive(lease)
    now[0] = 8.9  # within refreshed ttl
    assert s.sweep_leases() == []
    now[0] = 9.1  # past it
    assert s.sweep_leases() == [lease]
    assert s.get("w/1") is None
    assert events == [{"watch": events[0]["watch"], "event": "delete", "key": "w/1"}]


def test_store_pubsub_wildcard():
    s = KvStore()
    got = []
    s.subscribe("kv_events.>", got.append)
    assert s.publish("kv_events.w0", "e1") == 1
    assert s.publish("other.topic", "e2") == 0
    assert got[0]["value"] == "e1"


# ---------------------------------------------------------------------------
# server + client over TCP


async def start_test_store():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    port = server.sockets[0].getsockname()[1]
    return server, store, port


async def test_client_kv_watch_pubsub():
    server, store, port = await start_test_store()
    c = await KvClient(port=port).connect()
    await c.put("m/a", "1")
    assert await c.get("m/a") == "1"
    assert await c.get("m/missing") is None

    w = await c.watch_prefix("m/")
    assert [k for k, _, _ in w.initial] == ["m/a"]
    await c.put("m/b", "2")
    ev = await asyncio.wait_for(w.__anext__(), 2)
    assert (ev["event"], ev["key"], ev["value"]) == ("put", "m/b", "2")

    sub = await c.subscribe("events.>")
    c2 = await KvClient(port=port).connect()
    await c2.publish("events.x", "hello")
    ev = await asyncio.wait_for(sub.__anext__(), 2)
    assert ev["value"] == "hello"

    await c.close()
    await c2.close()
    server.close()


async def test_lease_keepalive_and_crash_expiry():
    server, store, port = await start_test_store()
    c = await KvClient(port=port).connect()
    lease = await c.lease_grant(0.3)
    await c.put("inst/1", "up", lease=lease.id)
    watcher = await KvClient(port=port).connect()
    w = await watcher.watch_prefix("inst/")

    # keep-alive holds the key past several TTLs
    await asyncio.sleep(1.0)
    assert await c.get("inst/1") == "up"

    # simulated crash: stop beating (but keep the connection open — leases
    # must expire by TTL, not connection state)
    lease._task.cancel()
    ev = await asyncio.wait_for(w.__anext__(), 5)
    assert ev["event"] == "delete" and ev["key"] == "inst/1"
    assert await c.get("inst/1") is None
    await c.close()
    await watcher.close()
    server.close()


# ---------------------------------------------------------------------------
# endpoint data plane


async def test_endpoint_stream_and_error():
    async def handler(payload):
        for i in range(payload["n"]):
            yield {"i": i}
        if payload.get("boom"):
            raise RuntimeError("boom")

    srv = EndpointServer(handler)
    host, port = await srv.start()
    got = [m async for m in call_endpoint(host, port, {"n": 3})]
    assert got == [{"i": 0}, {"i": 1}, {"i": 2}]

    with pytest.raises(EndpointStreamError, match="boom"):
        async for _ in call_endpoint(host, port, {"n": 1, "boom": True}):
            pass
    await srv.stop()


async def test_endpoint_client_drop_cancels_handler():
    cancelled = asyncio.Event()

    async def handler(payload):
        try:
            for i in range(10_000):
                await asyncio.sleep(0.01)
                yield {"i": i}
        finally:
            cancelled.set()

    srv = EndpointServer(handler)
    host, port = await srv.start()
    stream = call_endpoint(host, port, {})
    assert (await stream.__anext__())["i"] == 0
    await stream.aclose()
    await asyncio.wait_for(cancelled.wait(), 5)
    await srv.stop()


# ---------------------------------------------------------------------------
# the keystone: discovery + failover


async def test_component_discovery_and_failover():
    server, store, port = await start_test_store()
    rt = await DistributedRuntime.connect(port=port)
    ep = rt.namespace("test").component("worker").endpoint("generate")

    def make_handler(tag):
        async def handler(payload):
            yield {"from": tag, "echo": payload.get("x")}
        return handler

    w0 = await ep.serve(make_handler("w0"), worker_id="w0", lease_ttl_s=0.3)
    w1 = await ep.serve(make_handler("w1"), worker_id="w1", lease_ttl_s=0.3)

    client_rt = await DistributedRuntime.connect(port=port)
    cl = await client_rt.namespace("test").component("worker").endpoint("generate").client()
    await cl.wait_for_instances(2)

    # round-robin reaches both workers
    seen = set()
    for _ in range(4):
        async for m in cl.generate({"x": 1}):
            seen.add(m["from"])
    assert seen == {"w0", "w1"}

    # graceful shutdown: revoke deregisters immediately
    await w0.shutdown()
    t0 = asyncio.get_running_loop().time()
    while len(cl.instances) > 1:
        assert asyncio.get_running_loop().time() - t0 < 5
        await asyncio.sleep(0.02)
    for _ in range(3):
        async for m in cl.generate({"x": 2}):
            assert m["from"] == "w1"

    # crash: stop w1's keep-alive without revoking; lease expiry evicts it
    w1.lease._task.cancel()
    t0 = asyncio.get_running_loop().time()
    while len(cl.instances) > 0:
        assert asyncio.get_running_loop().time() - t0 < 5
        await asyncio.sleep(0.02)
    with pytest.raises(ConnectionError):
        async for m in cl.generate({"x": 3}):
            pass

    await cl.stop()
    await client_rt.close()
    await w1.server.stop()
    await rt.close()
    server.close()


async def test_put_with_stale_lease_is_in_band_error():
    """Advisor r2 (medium): a put against an expired/unknown lease must
    answer {"ok": false} in-band — not tear down the multiplexed
    connection (killing every watch and pending future on it)."""
    from dynamo_tpu.runtime.client import StoreError

    server, store, port = await start_test_store()
    c = await KvClient(port=port).connect()
    w = await c.watch_prefix("k/")
    with pytest.raises(StoreError):
        await c.put("k/x", "v", lease=999999)
    # connection and watch both survive
    assert await c.ping()
    await c.put("k/y", "1")
    ev = await asyncio.wait_for(w.__anext__(), 2)
    assert (ev["event"], ev["key"]) == ("put", "k/y")
    await c.close()
    server.close()


async def test_watch_snapshot_is_atomic_with_registration():
    """The watch op returns the snapshot atomically with registration — a
    put landing right around watch start is seen exactly once (either in
    the snapshot or as an event), never lost."""
    server, store, port = await start_test_store()
    writer = await KvClient(port=port).connect()
    await writer.put("a/0", "x")

    for i in range(1, 6):
        c = await KvClient(port=port).connect()
        # concurrent put racing the watch registration
        put_task = asyncio.create_task(writer.put(f"a/{i}", "y"))
        w = await c.watch_prefix("a/")
        await put_task
        seen = {k for k, _, _ in w.initial}
        if f"a/{i}" not in seen:
            ev = await asyncio.wait_for(w.__anext__(), 2)
            assert ev["key"] == f"a/{i}"
        await c.close()
    await writer.close()
    server.close()


async def test_lease_keepalive_retries_transient_failures():
    """Advisor r2 (low): one failed beat must not kill the lease — the
    client retries until a full TTL of silence."""
    server, store, port = await start_test_store()
    c = await KvClient(port=port).connect()
    lease = await c.lease_grant(1.2)

    # monkeypatch one transient failure into the keepalive path
    real = c.lease_keepalive
    fails = {"n": 1}

    async def flaky(lease_id):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise ConnectionError("transient blip")
        return await real(lease_id)

    c.lease_keepalive = flaky
    await asyncio.sleep(1.0)  # spans ≥2 beats incl. the failed one
    assert not lease.lost.is_set()
    assert store.lease_keepalive(lease.id)  # still live server-side
    await lease.revoke()
    await c.close()
    server.close()


# ---------------------------------------------------------------------------
# durable queues (JetStream-work-queue equivalent; prefill queue transport)


def test_store_queue_fifo_core():
    s = KvStore()
    assert s.qlen("q") == 0
    assert s.qpop("q") is None
    assert s.qpush("q", "a") == 1
    assert s.qpush("q", "b") == 2
    assert s.qpop("q") == "a"
    assert s.qpop("q") == "b"
    assert s.qpop("q") is None


async def test_queue_longpoll_and_fifo_over_wire():
    server, store, port = await start_test_store()
    producer = await KvClient(port=port).connect()
    consumer = await KvClient(port=port).connect()

    # values outlive the producer connection (durability across clients)
    await producer.qpush("prefill", "job1")
    await producer.qpush("prefill", "job2")
    assert await producer.qlen("prefill") == 2
    assert await consumer.qpop("prefill") == "job1"

    # parked long-poll served by the next push
    pop_task = asyncio.create_task(consumer.qpop("prefill2", timeout_s=5.0))
    await asyncio.sleep(0.1)  # let it park
    await producer.qpush("prefill2", "job3")
    assert await asyncio.wait_for(pop_task, 2) == "job3"

    # long-poll timeout returns None (served by the sweeper)
    assert await consumer.qpop("empty-q", timeout_s=0.2) is None

    # FIFO among waiters: two parked pops served in park order
    c2 = await KvClient(port=port).connect()
    p1 = asyncio.create_task(consumer.qpop("q3", timeout_s=5.0))
    await asyncio.sleep(0.05)
    p2 = asyncio.create_task(c2.qpop("q3", timeout_s=5.0))
    await asyncio.sleep(0.05)
    await producer.qpush("q3", "first")
    await producer.qpush("q3", "second")
    assert await asyncio.wait_for(p1, 2) == "first"
    assert await asyncio.wait_for(p2, 2) == "second"

    await producer.close()
    await consumer.close()
    await c2.close()
    server.close()


async def test_object_store_roundtrip():
    from dynamo_tpu.runtime.client import ObjectStore

    server, store, port = await start_test_store()
    c = await KvClient(port=port).connect()
    obj = ObjectStore(c)
    blob = bytes(range(256)) * 3
    await obj.put("cards", "llama-8b", blob)
    assert await obj.get("cards", "llama-8b") == blob
    assert await obj.get("cards", "missing") is None
    await obj.put("cards", "other", b"x")
    assert sorted(await obj.list("cards")) == ["llama-8b", "other"]
    await obj.delete("cards", "other")
    assert await obj.list("cards") == ["llama-8b"]
    await c.close()
    server.close()
