"""Resilience plane (dynamo_tpu/resilience/): retry/breaker policies,
health tracking, mid-stream migration with exactly-once delivery,
graceful drain, chaos hooks, and the resilience metrics contract.

The keystone is the migration differential: a worker killed mid-stream
under greedy decoding must leave the client with the BYTE-IDENTICAL token
sequence of an uninterrupted run — no drops, no duplicates — while
``dynamo_migration_total`` increments.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent,
    KvEventKind,
    StoredBlock,
)
from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.kv_router.scheduler import KvRouterConfig
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.resilience import (
    CHAOS,
    RESILIENCE,
    BreakerState,
    CircuitBreaker,
    DrainController,
    MigrationPolicy,
    RetryPolicy,
    WorkerDrainingError,
    WorkerHealthTracker,
    build_replay_request,
)
from dynamo_tpu.telemetry import TRACES
from dynamo_tpu.tokens import compute_block_hashes

BS = 4


@pytest.fixture(autouse=True)
def _reset_globals():
    RESILIENCE.reset()
    CHAOS.reset()
    yield
    RESILIENCE.reset()
    CHAOS.reset()


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


# ---------------------------------------------------------------------------
# RetryPolicy


def test_retry_policy_backoff_grows_and_jitters():
    p = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, multiplier=2.0,
                    jitter=0.5)
    for attempt, base in ((0, 0.1), (1, 0.2), (2, 0.4), (5, 1.0)):
        for _ in range(50):
            d = p.delay(attempt)
            assert base * 0.5 <= d <= base + 1e-9, (attempt, d)
    # jitter actually varies
    assert len({round(p.delay(1), 9) for _ in range(20)}) > 1


# ---------------------------------------------------------------------------
# CircuitBreaker (fake clock — the acceptance-criterion state machine)


def test_breaker_trips_after_consecutive_failures_and_readmits():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                       clock=clock)
    assert b.state is BreakerState.CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state is BreakerState.CLOSED  # below threshold
    b.record_failure()
    assert b.state is BreakerState.OPEN
    assert not b.allow()                   # open: no traffic
    clock.advance(4.9)
    assert not b.allow()                   # still inside the window
    clock.advance(0.2)
    assert b.allow()                       # ONE half-open probe
    assert b.state is BreakerState.HALF_OPEN
    assert not b.allow()                   # probe outstanding: no more
    b.record_success()                     # probe succeeded
    assert b.state is BreakerState.CLOSED
    assert b.allow()


def test_breaker_half_open_failure_reopens_with_fresh_timer():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                       clock=clock)
    b.record_failure()
    assert b.state is BreakerState.OPEN
    clock.advance(5.1)
    assert b.allow()                       # probe
    b.record_failure()                     # probe failed
    assert b.state is BreakerState.OPEN
    clock.advance(2.0)
    assert not b.allow()                   # timer restarted at the re-trip
    clock.advance(3.5)
    assert b.allow()


def test_breaker_success_resets_failure_streak():
    b = CircuitBreaker(failure_threshold=2)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state is BreakerState.CLOSED  # streak broken, never tripped


def test_breaker_stray_success_does_not_reopen_tripped_breaker():
    """Regression: a stream that was in flight when the breaker tripped
    completes later — its success says nothing about new requests and
    must not bypass the reset timeout + half-open probe."""
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0,
                       clock=clock)
    b.record_failure()
    b.record_failure()
    assert b.state is BreakerState.OPEN
    b.record_success()                     # stray in-flight completion
    assert b.state is BreakerState.OPEN
    assert not b.allow()                   # still inside the window
    clock.advance(5.1)
    assert b.allow()                       # probe protocol intact
    b.record_success()                     # THIS one resolves the probe
    assert b.state is BreakerState.CLOSED


# ---------------------------------------------------------------------------
# WorkerHealthTracker


def test_health_tracker_blocks_tripped_worker_then_readmits():
    clock = FakeClock()
    h = WorkerHealthTracker(failure_threshold=2, reset_timeout_s=5.0,
                            clock=clock)
    ids = ["a", "b"]
    assert h.blocked(ids) == set()
    h.record_failure("a")
    h.record_failure("a")
    assert h.blocked(ids) == {"a"}
    assert RESILIENCE.get("dynamo_resilience_breaker_open") == 1
    clock.advance(5.1)
    assert h.blocked(ids) == set()         # half-open probe available
    h.on_routed("a")                       # a request dispatches: probe
    h.record_success("a")                  # probe succeeded
    assert h.blocked(ids) == set()
    assert RESILIENCE.get("dynamo_resilience_breaker_open") == 0
    assert RESILIENCE.get("dynamo_resilience_breaker_trips_total") == 1


def test_health_tracker_probe_not_starved_by_routing_elsewhere():
    """Regression: blocked() must be side-effect free. A recovered
    worker's half-open probe is consumed only when a request actually
    dispatches to it (on_routed) — routing decisions that pick OTHER
    workers must not burn the grant and starve the recovered worker."""
    clock = FakeClock()
    h = WorkerHealthTracker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
    h.record_failure("a")
    clock.advance(5.1)
    # many routing decisions that all pick "b": "a" stays routable
    for _ in range(5):
        assert h.blocked(["a", "b"]) == set()
        h.on_routed("b")
    # finally a request dispatches to "a": that IS the probe
    h.on_routed("a")
    assert h.breaker("a").state is BreakerState.HALF_OPEN
    assert h.blocked(["a", "b"]) == {"a"}  # probe outstanding
    h.record_success("a")
    assert h.breaker("a").state is BreakerState.CLOSED
    assert h.blocked(["a", "b"]) == set()


def test_health_tracker_heartbeat_staleness():
    clock = FakeClock()
    h = WorkerHealthTracker(heartbeat_ttl_s=10.0, clock=clock)
    # never heartbeated: no signal, routable
    assert h.blocked(["a"]) == set()
    h.heartbeat("a")
    clock.advance(9.0)
    assert h.blocked(["a"]) == set()
    clock.advance(2.0)
    assert h.blocked(["a"]) == {"a"}       # lease-style expiry
    h.heartbeat("a")
    assert h.blocked(["a"]) == set()


# ---------------------------------------------------------------------------
# replay-request construction


def test_build_replay_request_shifts_budgets():
    req = PreprocessedRequest(
        token_ids=[1, 2, 3],
        stop_conditions=StopConditions(max_tokens=10, min_tokens=5),
    )
    r = build_replay_request(req, [7, 8])
    assert r.token_ids == [1, 2, 3, 7, 8]
    assert r.stop_conditions.max_tokens == 8
    assert r.stop_conditions.min_tokens == 3
    assert r.estimated_prefix_hit_num_blocks is None
    # the original request is untouched
    assert req.token_ids == [1, 2, 3]
    assert req.stop_conditions.max_tokens == 10


def test_build_replay_request_none_when_budget_spent():
    req = PreprocessedRequest(
        token_ids=[1], stop_conditions=StopConditions(max_tokens=2),
    )
    assert build_replay_request(req, [4, 5]) is None


# ---------------------------------------------------------------------------
# deterministic fake engines (continuation depends only on content, like
# a real LM under greedy decoding)


def _lcg_next(toks: list[int]) -> int:
    return (toks[-1] * 1103515245 + len(toks) * 12345 + 7) % 997


def lcg_sequence(prompt: list[int], n: int) -> list[int]:
    toks = list(prompt)
    out = []
    for _ in range(n):
        t = _lcg_next(toks)
        toks.append(t)
        out.append(t)
    return out


class LcgEngine:
    """Greedy 'model' whose next token is a pure function of the
    sequence so far — replaying prompt+emitted continues identically."""

    def __init__(self):
        self.served = 0

    async def generate(self, req: PreprocessedRequest):
        self.served += 1
        toks = list(req.token_ids)
        mt = req.stop_conditions.max_tokens or 8
        for i in range(mt):
            await asyncio.sleep(0)
            t = _lcg_next(toks)
            toks.append(t)
            fin = FinishReason.LENGTH if i == mt - 1 else None
            yield LLMEngineOutput(token_ids=[t], finish_reason=fin)


class AssassinEngine:
    """LcgEngine that dies mid-stream: after ``kill_after`` tokens of a
    request not yet in ``killed``, raise ConnectionError. ``killed`` is
    shared across the fleet so a migrated replay survives anywhere."""

    def __init__(self, kill_after: int, killed: set):
        self.inner = LcgEngine()
        self.kill_after = kill_after
        self.killed = killed

    async def generate(self, req: PreprocessedRequest):
        arm = req.request_id not in self.killed
        n = 0
        async for out in self.inner.generate(req):
            yield out
            n += len(out.token_ids)
            if arm and n >= self.kill_after:
                self.killed.add(req.request_id)
                raise ConnectionError("assassin: worker died mid-stream")


class DeadEngine:
    """Unreachable before the first token (connection refused shape)."""

    def __init__(self):
        self.attempts = 0

    async def generate(self, req):
        self.attempts += 1
        raise ConnectionError("connection refused")
        yield  # pragma: no cover — makes this an async generator


def make_push(engines: dict, **kw) -> KvPushRouter:
    router = KvRouter(BS, KvRouterConfig(router_temperature=0.0))
    return KvPushRouter(router, dict(engines), **kw)


def stored(worker, hashes, parent=0):
    return KvCacheEvent(
        kind=KvEventKind.STORED, worker_id=worker, parent_hash=parent,
        blocks=[StoredBlock(block_hash=h) for h in hashes],
    )


async def _drive(push, req):
    toks, finishes = [], []
    async for out in push.generate(req):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            finishes.append(out.finish_reason)
    return toks, finishes


def _req(prompt, max_tokens=12):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
    )


# ---------------------------------------------------------------------------
# pre-first-token re-route (satellite: previously untested path)


async def test_reroute_before_first_token_evicts_and_recovers():
    dead = DeadEngine()
    ok = LcgEngine()
    push = make_push({"dead": dead, "ok": ok})
    prompt = list(range(1, 17))
    # warm the DEAD worker's indexer entry so routing prefers it
    hashes = compute_block_hashes(prompt, BS)
    push.router.indexer.apply_event(stored("dead", hashes))

    toks, fins = await _drive(push, _req(prompt, max_tokens=6))
    assert toks == lcg_sequence(prompt, 6)
    assert fins == [FinishReason.LENGTH]
    assert dead.attempts == 1 and ok.served == 1
    # evicted: out of the worker table AND the indexer
    assert "dead" not in push.workers
    assert push.router.indexer.find_matches(hashes).scores == {}
    assert push.reroutes == 1
    assert RESILIENCE.get("dynamo_resilience_reroute_total") == 1
    assert RESILIENCE.get("dynamo_migration_total") == 0


async def test_all_workers_unreachable_raises():
    push = make_push({"d1": DeadEngine(), "d2": DeadEngine()})
    with pytest.raises(ConnectionError):
        await _drive(push, _req(range(1, 9)))
    assert not push.workers


# ---------------------------------------------------------------------------
# mid-stream migration (the differential acceptance criterion)


async def test_migration_differential_exactly_once():
    """Kill a worker mid-stream under greedy decoding: the client
    receives the byte-identical token sequence of an uninterrupted run
    (no drops, no duplicates) and dynamo_migration_total increments."""
    prompt = list(range(10, 26))
    expected = lcg_sequence(prompt, 12)

    killed: set = set()
    push = make_push({
        "w0": AssassinEngine(4, killed),
        "w1": AssassinEngine(4, killed),
    })
    toks, fins = await _drive(push, _req(prompt, max_tokens=12))
    assert toks == expected, "migrated stream diverged"
    assert fins == [FinishReason.LENGTH]
    assert push.migrations == 1
    assert len(killed) == 1
    assert RESILIENCE.get("dynamo_migration_total") == 1
    assert RESILIENCE.get("dynamo_migration_replayed_tokens_total") == 4
    assert RESILIENCE.get("dynamo_migration_failed_total") == 0


async def test_migration_trace_always_recorded():
    """Migrated requests are traced even when sampling skipped them."""
    prompt = list(range(30, 46))
    killed: set = set()
    push = make_push({
        "w0": AssassinEngine(3, killed),
        "w1": AssassinEngine(3, killed),
    })
    req = _req(prompt, max_tokens=8)
    TRACES.start(req.request_id, sampled=False)  # below the sample rate
    toks, _ = await _drive(push, req)
    assert toks == lcg_sequence(prompt, 8)
    tr = TRACES.finish(req.request_id)
    assert tr is not None and tr.sampled
    names = tr.span_names()
    assert "migrate" in names
    TRACES.clear()


async def test_migration_budget_spent_finishes_with_length():
    """A worker dying exactly at the token budget: the replay would be a
    zero-token tail — the router closes the stream with LENGTH instead
    (matching what the uninterrupted run would have returned)."""

    class DiesAtBudget:
        async def generate(self, req):
            toks = list(req.token_ids)
            for _ in range(req.stop_conditions.max_tokens):
                t = _lcg_next(toks)
                toks.append(t)
                yield LLMEngineOutput(token_ids=[t])  # never finishes
            raise ConnectionError("died holding the last token")

    prompt = list(range(50, 66))
    push = make_push({"w0": DiesAtBudget(), "w1": LcgEngine()})
    toks, fins = await _drive(push, _req(prompt, max_tokens=5))
    assert toks == lcg_sequence(prompt, 5)
    assert fins == [FinishReason.LENGTH]
    assert RESILIENCE.get("dynamo_migration_total") == 0


async def test_no_migration_after_finish_delivered():
    """Regression: a worker that delivers the finish output and THEN
    dies (before the stream close) must not trigger migration — the
    request is complete; replaying would regenerate past the stop point
    and emit tokens after a finish chunk."""

    class DiesAfterFinish:
        async def generate(self, req):
            toks = list(req.token_ids)
            for i in range(req.stop_conditions.max_tokens):
                t = _lcg_next(toks)
                toks.append(t)
                fin = (FinishReason.LENGTH
                       if i == req.stop_conditions.max_tokens - 1 else None)
                yield LLMEngineOutput(token_ids=[t], finish_reason=fin)
            raise ConnectionError("died after the finish frame")

    prompt = list(range(70, 86))
    push = make_push({"w0": DiesAfterFinish(), "w1": LcgEngine()})
    toks, fins = await _drive(push, _req(prompt, max_tokens=5))
    assert toks == lcg_sequence(prompt, 5)
    assert fins == [FinishReason.LENGTH]      # exactly ONE finish
    assert push.migrations == 0
    assert RESILIENCE.get("dynamo_migration_total") == 0


async def test_migration_exhausted_raises_and_counts_failure():
    killed: set = set()

    class AlwaysDies:
        async def generate(self, req):
            toks = list(req.token_ids)
            t = _lcg_next(toks)
            yield LLMEngineOutput(token_ids=[t])
            raise ConnectionError("always dies")

    push = make_push({"w0": AlwaysDies(), "w1": AlwaysDies()},
                     migration=MigrationPolicy(max_migrations=3))
    with pytest.raises(ConnectionError):
        await _drive(push, _req(range(1, 9), max_tokens=6))
    assert RESILIENCE.get("dynamo_migration_failed_total") >= 1
    assert len(killed) == 0  # unused; silences lint


async def test_migration_disabled_propagates():
    killed: set = set()
    push = make_push(
        {"w0": AssassinEngine(2, killed), "w1": AssassinEngine(2, killed)},
        migration=MigrationPolicy(enabled=False),
    )
    with pytest.raises(ConnectionError):
        await _drive(push, _req(range(1, 9), max_tokens=8))


# ---------------------------------------------------------------------------
# breaker-aware routing


async def test_breaker_excludes_failing_worker_from_routing():
    clock = FakeClock()
    health = WorkerHealthTracker(failure_threshold=2, reset_timeout_s=30.0,
                                 clock=clock)
    killed: set = set()

    class DiesEveryTime:
        def __init__(self):
            self.calls = 0

        async def generate(self, req):
            self.calls += 1
            toks = list(req.token_ids)
            t = _lcg_next(toks)
            yield LLMEngineOutput(token_ids=[t])
            raise ConnectionError("mid-stream death")

    bad = DiesEveryTime()
    ok = LcgEngine()
    push = make_push({"bad": bad, "ok": ok}, health=health)
    # route several requests; "bad" fails mid-stream whenever chosen and
    # migration recovers onto "ok". After 2 failures the breaker trips
    # and "bad" stops receiving traffic entirely.
    for i in range(8):
        prompt = list(range(i * 7 + 1, i * 7 + 9))
        toks, _ = await _drive(push, _req(prompt, max_tokens=4))
        assert toks == lcg_sequence(prompt, 4)
    assert health.breaker("bad").state is BreakerState.OPEN
    calls_at_trip = bad.calls
    for i in range(3):
        prompt = list(range(100 + i * 7, 108 + i * 7))
        await _drive(push, _req(prompt, max_tokens=4))
    assert bad.calls == calls_at_trip  # tripped: no traffic
    assert "bad" in push.workers       # NOT evicted — breaker, not lease
    assert len(killed) == 0


# ---------------------------------------------------------------------------
# clear_kv_blocks indexer invalidation (satellite: previously untested)


async def test_clear_kv_blocks_invalidates_indexer():
    class Clearable(LcgEngine):
        def __init__(self, n):
            super().__init__()
            self.n = n
            self.cleared = 0

        async def clear_kv_blocks(self):
            self.cleared += 1
            return self.n

    e0, e1 = Clearable(3), Clearable(5)
    push = make_push({"w0": e0, "w1": e1})
    hashes = compute_block_hashes(list(range(1, 17)), BS)
    push.router.indexer.apply_event(stored("w0", hashes))
    push.router.indexer.apply_event(stored("w1", hashes[:2]))
    assert push.router.indexer.find_matches(hashes).scores == {
        "w0": 4, "w1": 2,
    }
    total = await push.clear_kv_blocks()
    assert total == 8
    assert e0.cleared == 1 and e1.cleared == 1
    # the radix view is stale by construction: dropped for every worker
    assert push.router.indexer.find_matches(hashes).scores == {}
    # workers stay routable (clearing caches is not a failure)
    assert set(push.workers) == {"w0", "w1"}


# ---------------------------------------------------------------------------
# graceful drain


async def test_drain_controller_finishes_inflight_then_refuses():
    from dynamo_tpu.mocker import MockerArgs, MockerEngine

    eng = MockerEngine(MockerArgs(speedup_ratio=1.0, page_size=BS,
                                  num_pages=64,
                                  decode_time_per_step_s=0.005))
    stream = eng.generate(_req(list(range(1, 9)), max_tokens=12))
    first = await stream.__anext__()          # admitted + first token
    assert first.token_ids
    controller = DrainController(eng, timeout_s=10.0)
    ev = controller.request_drain(reason="test")
    assert controller.state == "draining"
    # new admissions refused with the RETRIABLE error class
    with pytest.raises(WorkerDrainingError):
        async for _ in eng.generate(_req(list(range(1, 9)))):
            pass
    # the in-flight request runs to completion
    got = [t for t in first.token_ids]
    async for out in stream:
        got.extend(out.token_ids)
    assert len(got) == 12
    await asyncio.wait_for(ev.wait(), timeout=10.0)
    assert controller.state == "drained"
    assert RESILIENCE.get("dynamo_resilience_drains_total") == 1
    assert RESILIENCE.get("dynamo_resilience_draining") == 0
    await eng.stop()


async def test_drain_controller_hooks_fire_in_order():
    events = []

    class InstantEngine:
        def begin_drain(self):
            events.append("begin")

        def drained(self):
            return True

    async def dereg():
        events.append("dereg")

    controller = DrainController(
        InstantEngine(), on_deregister=dereg,
        on_drained=lambda: events.append("done"),
    )
    ev = controller.request_drain()
    await asyncio.wait_for(ev.wait(), timeout=5.0)
    # admissions stop synchronously, then deregister, then completion
    assert events == ["begin", "dereg", "done"]
    # idempotent
    assert controller.request_drain() is ev


# ---------------------------------------------------------------------------
# planner scale-down drains instead of killing (acceptance criterion)


async def test_local_connector_scale_down_drains_gracefully(tmp_path):
    """LocalConnector retirement sends SIGTERM and grants the drain
    grace: a worker that finishes its work and exits is never
    SIGKILLed."""
    import sys

    from dynamo_tpu.planner import LocalConnector

    marker = tmp_path / "drained"
    script = (
        "import signal, sys, time\n"
        "def h(*a):\n"
        f"    open({str(marker)!r}, 'w').write('ok')\n"
        "    sys.exit(0)\n"
        "signal.signal(signal.SIGTERM, h)\n"
        "time.sleep(60)\n"
    )
    conn = LocalConnector([sys.executable, "-c", script],
                          drain_grace_s=10.0)
    await conn.set_replicas(1)
    proc = conn.procs[0]
    await asyncio.sleep(0.3)  # let the handler install
    await conn.set_replicas(0)
    assert conn.drains_started == 1
    for _ in range(100):
        if marker.exists() and proc.poll() is not None:
            break
        await asyncio.sleep(0.1)
    assert marker.exists(), "worker was killed before it could drain"
    assert proc.poll() == 0  # clean exit, not SIGKILL
    await conn.shutdown()


async def test_local_connector_kills_after_drain_grace(tmp_path):
    """A worker that ignores SIGTERM is SIGKILLed after the grace."""
    import signal as _signal
    import sys

    from dynamo_tpu.planner import LocalConnector

    script = (
        "import signal, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "time.sleep(60)\n"
    )
    conn = LocalConnector([sys.executable, "-c", script],
                          drain_grace_s=0.4)
    await conn.set_replicas(1)
    proc = conn.procs[0]
    await asyncio.sleep(0.3)
    await conn.set_replicas(0)
    for _ in range(100):
        if proc.poll() is not None:
            break
        await asyncio.sleep(0.1)
    assert proc.poll() == -_signal.SIGKILL
    await conn.shutdown()


# ---------------------------------------------------------------------------
# chaos hooks


def test_chaos_configure_grammar():
    CHAOS.configure("kill_worker:p=0.5:after=3,delay:t=0.05,"
                    "stall_stream:t=2:once")
    k = CHAOS.points["kill_worker"]
    assert k.armed and k.probability == 0.5 and k.after_outputs == 3
    d = CHAOS.points["delay"]
    assert d.armed and d.delay_s == 0.05
    s = CHAOS.points["stall_stream"]
    assert s.armed and s.once
    assert not CHAOS.points["drop_response"].armed
    with pytest.raises(ValueError):
        CHAOS.configure("explode")


async def _numbers(n):
    for i in range(n):
        yield i


async def test_chaos_kill_worker_drops_stream():
    CHAOS.arm("kill_worker", after_outputs=2, once=True)
    got = []
    with pytest.raises(ConnectionResetError):
        async for item in CHAOS.wrap_stream(_numbers(6)):
            got.append(item)
    assert got == [0, 1]
    assert not CHAOS.points["kill_worker"].armed  # once: self-disarmed
    assert CHAOS.points["kill_worker"].injected_total == 1
    assert RESILIENCE.get(
        "dynamo_resilience_chaos_injections_total") == 1
    # disarmed: streams flow clean again
    assert [i async for i in CHAOS.wrap_stream(_numbers(3))] == [0, 1, 2]


async def test_chaos_drop_response_swallows_one():
    CHAOS.arm("drop_response", once=True)
    got = [i async for i in CHAOS.wrap_stream(_numbers(4))]
    assert got == [1, 2, 3]  # first output dropped, then disarmed


async def test_chaos_once_kill_fires_exactly_once_across_streams():
    """Regression: a once-fused kill latched by several CONCURRENT
    streams must fire on exactly one of them — the others re-check the
    armed fuse at injection time."""
    CHAOS.arm("kill_worker", after_outputs=1, once=True)
    g1 = CHAOS.wrap_stream(_numbers(4))
    g2 = CHAOS.wrap_stream(_numbers(4))
    assert await g1.__anext__() == 0   # both streams latch their trigger
    assert await g2.__anext__() == 0
    with pytest.raises(ConnectionResetError):
        await g1.__anext__()           # first injection disarms the fuse
    got = [0]
    async for item in g2:              # survivor streams to completion
        got.append(item)
    assert got == [0, 1, 2, 3]
    assert CHAOS.points["kill_worker"].injected_total == 1


async def test_disagg_wrapper_drain_rejects_before_remote_prefill():
    """Regression: a draining disagg decode worker must refuse BEFORE
    the remote-prefill decision — not after paying a cross-worker KV
    transfer for a request it then rejects."""
    from dynamo_tpu.disagg import DisaggDecodeEngine
    from dynamo_tpu.mocker import MockerArgs, MockerEngine

    inner = MockerEngine(MockerArgs(speedup_ratio=100.0, page_size=BS,
                                    num_pages=64))
    # rt=None: any touch of the control plane in the drained path would
    # raise AttributeError, failing the test
    eng = DisaggDecodeEngine(inner, rt=None)
    eng.begin_drain()
    with pytest.raises(WorkerDrainingError):
        async for _ in eng.generate(_req(list(range(1, 9)))):
            pass
    assert eng.drained()
    await inner.stop()


# ---------------------------------------------------------------------------
# trace sampling (--trace-sample-rate satellite)


def test_trace_sampling_shell_dropped_and_promotable():
    from dynamo_tpu.telemetry.trace import span_now
    import time as _time

    TRACES.clear()
    tr = TRACES.start("unsampled-1", sampled=False)
    assert not TRACES.add_span("unsampled-1",
                               span_now("route", _time.monotonic()))
    assert tr.spans == []
    assert TRACES.finish("unsampled-1") is not None
    assert TRACES.get("unsampled-1") is None  # dropped, not parked

    TRACES.start("promoted-1", sampled=False)
    assert TRACES.promote("promoted-1")
    assert TRACES.add_span("promoted-1",
                           span_now("migrate", _time.monotonic()))
    TRACES.finish("promoted-1")
    got = TRACES.get("promoted-1")
    assert got is not None and got.span_names() == ["migrate"]
    TRACES.clear()


def test_http_service_sampling_rate_zero_keeps_shells_out_of_ring():
    from dynamo_tpu.frontend.service import HttpService

    svc = HttpService(trace_sample_rate=0.0)
    assert svc.trace_sample_rate == 0.0


# ---------------------------------------------------------------------------
# metrics contract (families render with HELP/TYPE on every surface)


def test_resilience_metrics_render_families():
    RESILIENCE.inc("dynamo_migration_total")
    RESILIENCE.set("dynamo_resilience_draining", 1)
    text = RESILIENCE.render()
    assert "# HELP dynamo_migration_total" in text
    assert "# TYPE dynamo_migration_total counter" in text
    assert "dynamo_migration_total 1" in text
    assert "# TYPE dynamo_resilience_draining gauge" in text
    assert "dynamo_resilience_draining 1" in text


def test_resilience_metrics_on_all_three_surfaces():
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.system_server import SystemServer

    RESILIENCE.inc("dynamo_migration_total", 2)
    sys_text = SystemServer(None, worker_id="w0").render()
    exp_text = MetricsExporter(kv=None).render()
    svc = HttpService()
    import asyncio as _a

    async def front():
        req = None  # handle_metrics ignores the request object
        resp = await svc.handle_metrics(req)
        return resp.body.decode()

    front_text = _a.get_event_loop_policy().new_event_loop().run_until_complete(front())
    for text in (sys_text, exp_text, front_text):
        assert "dynamo_migration_total 2" in text
        assert "# TYPE dynamo_resilience_breaker_trips_total counter" in text
