"""Mocker engine tests (reference mocker/engine.rs + kv_manager tests).

The mocker must behave like a real engine on the AsyncEngine contract:
deterministic streams, prefix-cache events, preemption under page pressure,
metrics — all on CPU with no JAX.
"""
import asyncio

from dynamo_tpu.kv_router.protocols import KvEventKind
from dynamo_tpu.mocker import MockerArgs, MockerEngine
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.tokens import compute_block_hashes


def req(prompt, max_tokens=8, **stop_kw):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=True, **stop_kw
        ),
    )


async def collect(eng, r):
    toks, finish = [], None
    async for out in eng.generate(r):
        toks.extend(out.token_ids)
        if out.finish_reason:
            finish = out.finish_reason
    return toks, finish


async def test_mocker_deterministic_and_finishes():
    eng = MockerEngine(MockerArgs(speedup_ratio=100.0))
    prompt = list(range(1, 20))
    t1, f1 = await collect(eng, req(prompt, 10))
    t2, f2 = await collect(eng, req(prompt, 10))
    assert t1 == t2
    assert len(t1) == 10
    assert f1.value == "length"
    # tokens cycle the prompt deterministically
    assert t1 == [prompt[(i + len(prompt)) % len(prompt)] for i in range(10)]
    await eng.stop()


async def test_mocker_eos_stop():
    eng = MockerEngine(MockerArgs(speedup_ratio=100.0))
    prompt = list(range(1, 10))
    # first generated token is prompt[0]=1 -> make it the stop id
    r = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=10, stop_token_ids=[2]),
    )
    toks, finish = await collect(eng, r)
    assert finish.value == "eos"
    assert 2 not in toks
    await eng.stop()


async def test_mocker_kv_events_and_prefix_hits():
    events = []
    eng = MockerEngine(
        MockerArgs(speedup_ratio=100.0, page_size=4), on_kv_event=events.append
    )
    prompt = list(range(1, 14))  # 13 tokens = 3 full blocks + tail
    await collect(eng, req(prompt, 4))
    stored = [e for e in events if e.kind == KvEventKind.STORED]
    assert stored, "prefill must publish stored-block events"
    # hashes must match the shared chained-hash scheme (router parity)
    want = compute_block_hashes(prompt[:12], 4)
    got = [b.block_hash for e in stored for b in e.blocks]
    assert got[:3] == want
    hits_before = eng.allocator.hit_blocks
    await collect(eng, req(prompt, 4))
    assert eng.allocator.hit_blocks > hits_before
    await eng.stop()


async def test_mocker_preemption_under_pressure():
    eng = MockerEngine(
        MockerArgs(speedup_ratio=100.0, num_pages=12, page_size=4,
                   max_decode_slots=4)
    )
    prompts = [list(range(1 + 5 * i, 12 + 5 * i)) for i in range(4)]
    outs = await asyncio.gather(
        *[collect(eng, req(p, 30)) for p in prompts]
    )
    assert all(len(t) == 30 for t, _ in outs)
    assert eng.preemptions > 0
    # determinism preserved across preemption
    solo, _ = await collect(eng, req(prompts[0], 30))
    assert outs[0][0] == solo
    await eng.stop()


async def test_mocker_metrics_and_cancellation():
    seen = []
    eng = MockerEngine(
        MockerArgs(speedup_ratio=10.0), on_metrics=seen.append
    )
    gen = eng.generate(req(list(range(1, 30)), 1000))
    first = await gen.__anext__()
    assert first.token_ids
    await gen.aclose()  # drop mid-stream: must cancel + free pages
    for _ in range(100):
        await asyncio.sleep(0.01)
        if eng.allocator.active_pages == 0:
            break
    assert eng.allocator.active_pages == 0
    assert seen and seen[-1].kv_stats.kv_total_blocks > 0
    await eng.stop()
