"""Trace synthesizer/analyzer tests (reference benchmarks/data_generator:
mooncake trace format with hash_ids prefix sharing)."""
import json

from dynamo_tpu.data_generator import (
    TraceConfig,
    analyze,
    read_trace,
    synthesize,
    write_trace,
)


def test_synthesize_deterministic_and_sorted():
    cfg = TraceConfig(num_requests=50, seed=7)
    a = synthesize(cfg)
    b = synthesize(cfg)
    assert a == b                                   # seeded
    ts = [r["timestamp"] for r in a]
    assert ts == sorted(ts)
    for r in a:
        assert r["input_length"] >= 1 and r["output_length"] >= 1
        assert len(r["hash_ids"]) >= 1


def test_multi_turn_prefix_sharing():
    """Later turns of a session must reuse earlier turns' blocks — the
    property KV routing/offload benchmarks depend on."""
    cfg = TraceConfig(num_requests=200, num_sessions=4, turns_mean=8.0,
                      seed=1)
    records = synthesize(cfg)
    stats = analyze(records)
    assert stats["prefix_reuse_ratio"] > 0.2
    # single-turn trace (sessions reset every time): near-zero reuse
    one_shot = synthesize(TraceConfig(num_requests=200, num_sessions=200,
                                      turns_mean=1.0, seed=1))
    assert analyze(one_shot)["prefix_reuse_ratio"] < \
        stats["prefix_reuse_ratio"]


def test_trace_roundtrip_and_analyze(tmp_path):
    cfg = TraceConfig(num_requests=30, request_rate_per_s=10.0, seed=3)
    records = synthesize(cfg)
    path = str(tmp_path / "trace.jsonl")
    write_trace(records, path)
    back = list(read_trace(path))
    assert back == records
    stats = analyze(back)
    assert stats["num_requests"] == 30
    assert 1.0 < stats["request_rate_per_s"] < 100.0
    assert stats["unique_blocks"] > 0
    # mooncake-compatible field names on disk
    first = json.loads(open(path).readline())
    assert set(first) == {"timestamp", "input_length", "output_length",
                          "hash_ids"}
