"""Trace synthesizer/analyzer tests (reference benchmarks/data_generator:
mooncake trace format with hash_ids prefix sharing)."""
import json

from dynamo_tpu.data_generator import (
    TraceConfig,
    analyze,
    read_trace,
    synthesize,
    write_trace,
)


def test_synthesize_deterministic_and_sorted():
    cfg = TraceConfig(num_requests=50, seed=7)
    a = synthesize(cfg)
    b = synthesize(cfg)
    assert a == b                                   # seeded
    ts = [r["timestamp"] for r in a]
    assert ts == sorted(ts)
    for r in a:
        assert r["input_length"] >= 1 and r["output_length"] >= 1
        assert len(r["hash_ids"]) >= 1


def test_multi_turn_prefix_sharing():
    """Later turns of a session must reuse earlier turns' blocks — the
    property KV routing/offload benchmarks depend on."""
    cfg = TraceConfig(num_requests=200, num_sessions=4, turns_mean=8.0,
                      seed=1)
    records = synthesize(cfg)
    stats = analyze(records)
    assert stats["prefix_reuse_ratio"] > 0.2
    # single-turn trace (sessions reset every time): near-zero reuse
    one_shot = synthesize(TraceConfig(num_requests=200, num_sessions=200,
                                      turns_mean=1.0, seed=1))
    assert analyze(one_shot)["prefix_reuse_ratio"] < \
        stats["prefix_reuse_ratio"]


def test_trace_roundtrip_and_analyze(tmp_path):
    cfg = TraceConfig(num_requests=30, request_rate_per_s=10.0, seed=3)
    records = synthesize(cfg)
    path = str(tmp_path / "trace.jsonl")
    write_trace(records, path)
    back = list(read_trace(path))
    assert back == records
    stats = analyze(back)
    assert stats["num_requests"] == 30
    assert 1.0 < stats["request_rate_per_s"] < 100.0
    assert stats["unique_blocks"] > 0
    # mooncake-compatible field names on disk
    first = json.loads(open(path).readline())
    assert set(first) == {"timestamp", "input_length", "output_length",
                          "hash_ids"}


def test_trace_request_determinism_and_sharing():
    """Equal hash prefixes must produce equal token prefixes EVEN when the
    records' input_length/hash ratios differ (real synthesize() output) —
    the property that makes trace replay exercise the prefix cache."""
    from dynamo_tpu.launch.run import _trace_request

    bs = 16
    a = _trace_request({"input_length": 32, "output_length": 8,
                        "hash_ids": [1, 2]}, bs)
    b = _trace_request({"input_length": 48, "output_length": 8,
                        "hash_ids": [1, 2, 3]}, bs)
    assert a.token_ids == b.token_ids[: len(a.token_ids)]  # shared prefix
    # divergent ratios (the realistic case): 27/2 vs 41/3 hash coverage
    d = _trace_request({"input_length": 27, "output_length": 8,
                        "hash_ids": [1, 2]}, bs)
    e = _trace_request({"input_length": 41, "output_length": 8,
                        "hash_ids": [1, 2, 3]}, bs)
    assert d.token_ids == e.token_ids[: len(d.token_ids)]
    c = _trace_request({"input_length": 32, "output_length": 8,
                        "hash_ids": [9, 10]}, bs)
    assert c.token_ids != a.token_ids
    assert a.stop_conditions.max_tokens == 8
    assert all(0 < t < 2**31 for t in a.token_ids)


def test_trace_request_sharing_on_real_synthesized_trace():
    """End-to-end property on actual datagen output: every hash-prefix
    pair in the trace yields a shared token prefix through _trace_request
    (with block_size matching the trace's)."""
    from dynamo_tpu.launch.run import _trace_request

    bs = 16
    records = synthesize(TraceConfig(num_requests=40, num_sessions=4,
                                     turns_mean=6.0, block_size=bs,
                                     seed=5))
    reqs = [_trace_request(r, bs) for r in records]
    checked = 0
    for i, ri in enumerate(records):
        for j, rj in enumerate(records):
            hi, hj = ri["hash_ids"], rj["hash_ids"]
            if i != j and len(hi) < len(hj) and hj[: len(hi)] == hi:
                shared_tokens = min(len(hi) * bs,
                                    len(reqs[i].token_ids),
                                    len(reqs[j].token_ids))
                assert reqs[i].token_ids[:shared_tokens] == \
                    reqs[j].token_ids[:shared_tokens]
                checked += 1
    assert checked > 5  # the trace really contains sharing to check
