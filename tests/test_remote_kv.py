"""KVBM G4 remote tier (reference block_manager.rs:69-82 CacheLevel::G4,
storage/nixl.rs:403): a COLD worker whose G1/G2/G3 tiers miss a prefix
fetches the sealed pages from a PEER worker's pool over the transfer
plane (hash-addressed one-sided read), lands them in its G2 host tier,
and onboards them through the normal path — serving the same tokens as
the warm worker without recomputing the prefix."""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_transfer import (
    BlocksetDescriptor,
    BlockTransferServer,
    KvCacheLayout,
    RemoteKvFetcher,
    publish_descriptor,
    read_remote_hashes,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store

PS = 16


def _ecfg(**kw):
    base = dict(
        num_pages=64, page_size=PS, max_pages_per_seq=8,
        max_decode_slots=2, prefill_buckets=(32, 64),
        cache_dtype="float32", flush_every=2, max_inflight_rounds=1,
    )
    base.update(kw)
    return EngineConfig(**base)


async def _collect(eng, prompt, n=6):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n, ignore_eos=True),
    )
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


@pytest.mark.asyncio_timeout(180)
async def test_cold_worker_onboards_prefix_from_peer_pool():
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    server, _store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kv_a = await KvClient(port=port).connect()
    kv_b = await KvClient(port=port).connect()

    warm = TpuEngine(cfg, _ecfg(), params=params,
                     mesh_config=MeshConfig(tp=1))
    cold = TpuEngine(cfg, _ecfg(host_offload_pages=16), params=params,
                     mesh_config=MeshConfig(tp=1))
    try:
        # warm worker seals 3 full blocks of prefix
        prompt = list(range(1, PS * 3 + 4))
        warm_toks = await _collect(warm, prompt)

        # warm worker's pool on the transfer plane, hash-addressed
        srv = BlockTransferServer(
            read_fn=warm.export_pages,
            read_hashes_fn=warm.export_pages_by_hash,
        )
        host, sport = await srv.start()
        await publish_descriptor(kv_a, "g4", BlocksetDescriptor(
            worker_id="warm", host=host, port=sport,
            layout=KvCacheLayout(
                num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
                page_size=PS, head_dim=cfg.head_dim, dtype="float32",
            ),
        ))

        # direct hash read: peer resolves the committed run
        hashes = [b.block_hash for b in
                  __import__("dynamo_tpu.tokens", fromlist=["x"])
                  .TokenBlockSequence.from_tokens(prompt, PS).blocks[:3]]
        found, data = await read_remote_hashes(host, sport, hashes)
        assert found == 3
        assert data.shape[3] == 3

        # cold worker: G4 fetch -> G2 -> onboard; same tokens, no
        # recompute of the cached prefix
        cold.remote_kv = RemoteKvFetcher(kv_b, "g4", "cold")
        cold_toks = await _collect(cold, prompt)
        assert cold_toks == warm_toks
        assert cold.remote_kv.hits == 1
        assert cold.remote_onboard_blocks == 3
        assert cold.offload.onboard_hits >= 3  # onboarded, not recomputed

        # second request on the cold worker: now a pure LOCAL hit
        fetches = cold.remote_kv.fetches
        again = await _collect(cold, prompt)
        assert again == warm_toks
        assert cold.remote_kv.fetches == fetches  # no remote round-trip

        await srv.stop()
    finally:
        await warm.stop()
        await cold.stop()
        await kv_a.close()
        await kv_b.close()
        server.close()


@pytest.mark.asyncio_timeout(120)
async def test_remote_fetch_misses_and_dead_peers_are_harmless():
    cfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(cfg, 0)
    server, _store = await serve_store(port=0, sweep_interval_s=0.1)
    port = server.sockets[0].getsockname()[1]
    kv = await KvClient(port=port).connect()
    # a descriptor pointing at a dead port
    await publish_descriptor(kv, "g4m", BlocksetDescriptor(
        worker_id="gone", host="127.0.0.1", port=1,
        layout=KvCacheLayout(num_layers=1, num_kv_heads=1, page_size=PS,
                             head_dim=4, dtype="float32"),
    ))
    eng = TpuEngine(cfg, _ecfg(host_offload_pages=8), params=params,
                    mesh_config=MeshConfig(tp=1))
    eng.remote_kv = RemoteKvFetcher(kv, "g4m", "me", timeout_s=0.5)
    try:
        toks = await _collect(eng, list(range(1, PS * 2 + 3)))
        assert len(toks) == 6  # served fine despite the dead peer
        assert eng.remote_kv.fetches >= 1
        assert eng.remote_kv.hits == 0
    finally:
        await eng.stop()
        await kv.close()
        server.close()
