"""Batched cross-slot drafting + acceptance-adaptive K (dynamo_tpu/spec/).

Three guarantees on top of tests/test_spec.py's differential keystone:

  - the AdaptiveKController walks each slot's effective K on its rolling
    acceptance rate (grow/shrink/de-speculate thresholds), and greedy
    output stays token-identical to non-speculative decode even while K
    adapts mid-stream;
  - drafting for N speculating slots issues O(1) device dispatches per
    round (ONE llama.batch_draft program), not O(N*K) — and produces
    exactly the tokens the per-slot path produced;
  - the satellite fixes hold: padded prefix loads clamp to the ctx
    region instead of crashing the round, and emits to a closed client
    event loop no longer mask the original engine failure.
"""
import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, WorkerStats
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.spec.decoder import AdaptiveKController, SpecDecoder
from tests.test_spec import _prompts, make_engine, run_engine

PS = 16


def make_controller(**kw):
    k_max = kw.pop("k_max", 8)
    k_min = kw.pop("k_min", 1)
    base = dict(grow_at=0.8, shrink_at=0.4, despec_at=0.125,
                ewma=0.75, min_obs=8)
    base.update(kw)
    return AdaptiveKController(k_max, k_min, **base)


# ---------------------------------------------------------------------------
# AdaptiveKController (pure host)

def test_adaptive_k_starts_at_cap():
    c = make_controller(k_max=8)
    assert c.k_for(0) == 8
    assert c.k_for(3) == 8  # every slot, not just observed ones


def test_adaptive_k_shrinks_on_low_acceptance_to_floor():
    c = make_controller(k_max=4, k_min=2)
    for _ in range(20):
        c.observe(0, accepted=0, k_used=c.k_for(0))
    assert c.k_for(0) == 2            # floored at k_min
    assert c.shrink_total >= 2        # 4 -> 3 -> 2


def test_adaptive_k_grows_back_on_high_acceptance():
    c = make_controller(k_max=8, k_min=1)
    for _ in range(20):
        c.observe(0, accepted=0, k_used=c.k_for(0))
    assert c.k_for(0) == 1
    for _ in range(30):
        c.observe(0, accepted=c.k_for(0), k_used=c.k_for(0))
    assert c.k_for(0) == 8
    assert c.grow_total >= 7


def test_adaptive_k_hysteresis_band_holds_k():
    """Rates between shrink_at and grow_at leave K untouched."""
    c = make_controller(k_max=8, k_min=1)
    for _ in range(16):
        c.observe(0, accepted=5, k_used=8)   # 0.625: inside the band
    assert c.k_for(0) == 8
    assert c.grow_total == 0 and c.shrink_total == 0


def test_adaptive_k_despec_needs_min_obs_and_collapse():
    c = make_controller(k_max=4, min_obs=8)
    for i in range(7):
        c.observe(0, accepted=0, k_used=4)
        assert not c.should_despec(0)      # too few observations
    c.observe(0, accepted=0, k_used=4)
    assert c.should_despec(0)              # rate 0 <= despec_at, obs >= 8
    # a healthy slot never de-speculates
    for _ in range(20):
        c.observe(1, accepted=4, k_used=4)
    assert not c.should_despec(1)


def test_adaptive_k_release_forgets_slot_state():
    c = make_controller(k_max=4)
    for _ in range(10):
        c.observe(0, accepted=0, k_used=4)
    assert c.k_for(0) < 4
    c.release(0)
    assert c.k_for(0) == 4
    assert not c.should_despec(0)


def test_adaptive_k_ewma_recovers_from_one_bad_step():
    """One rejected round must not collapse a slot with a good history."""
    c = make_controller(k_max=4, min_obs=1)
    for _ in range(10):
        c.observe(0, accepted=4, k_used=4)
    c.observe(0, accepted=0, k_used=4)
    assert not c.should_despec(0)          # EWMA keeps rate ~0.75


def test_adaptive_branch_starts_wide_narrows_on_high_acceptance():
    """Tree axis: a fresh stream hedges WIDE (m = m_max); sustained
    acceptance walks it deep-and-narrow — K up, branches down to 1."""
    c = make_controller(k_max=8, k_min=1, m_max=4)
    assert c.m_for(0) == 4 and c.m_for(7) == 4   # every slot starts wide
    for _ in range(20):
        c.observe(0, accepted=c.k_for(0), k_used=c.k_for(0))
    assert c.k_for(0) == 8
    assert c.m_for(0) == 1
    assert c.branch_shrink_total >= 3            # 4 -> 3 -> 2 -> 1


def test_adaptive_branch_widens_back_on_low_acceptance():
    """Early rejection is exactly what sibling branches catch: low
    acceptance walks the shape shallow-and-wide — K down, branches up,
    bounded by m_max."""
    c = make_controller(k_max=8, k_min=1, m_max=4)
    for _ in range(20):
        c.observe(0, accepted=c.k_for(0), k_used=c.k_for(0))
    assert c.m_for(0) == 1
    for _ in range(30):
        c.observe(0, accepted=0, k_used=c.k_for(0))
    assert c.m_for(0) == 4
    assert c.k_for(0) == 1
    assert c.branch_grow_total >= 3


def test_adaptive_branch_hysteresis_and_tree_off_pins_m_one():
    """Mid-band rates hold the branch fan where it is; a linear-chain
    controller (m_max=1, tree off) never moves off m=1."""
    c = make_controller(k_max=8, m_max=4)
    for _ in range(16):
        c.observe(0, accepted=5, k_used=8)       # 0.625: inside the band
    assert c.m_for(0) == 4
    assert c.branch_grow_total == 0 and c.branch_shrink_total == 0
    lin = make_controller(k_max=8)               # m_max defaults to 1
    for _ in range(16):
        lin.observe(0, accepted=0, k_used=8)
    assert lin.m_for(0) == 1
    assert lin.branch_grow_total == 0


def test_adaptive_branch_despec_on_collapse_and_release_resets():
    """Hedging wider must not save a dead stream: a slot already at
    m_max with collapsed acceptance still de-speculates, and release()
    hands the lane back with the full wide shape."""
    c = make_controller(k_max=4, m_max=4, min_obs=8)
    for _ in range(12):
        c.observe(0, accepted=0, k_used=4)
    assert c.m_for(0) == 4                       # saturated wide...
    assert c.should_despec(0)                    # ...and still despecs
    assert c.k_for(0) < 4
    c.release(0)
    assert c.k_for(0) == 4 and c.m_for(0) == 4
    assert not c.should_despec(0)


def test_round_m_buckets_to_pow2_clamped_at_branches():
    cfg = ModelConfig.tiny(dtype="float32")
    dec = SpecDecoder(
        cfg, EngineConfig(speculative="ngram", num_speculative_tokens=4,
                          spec_tree=True, spec_branches=4),
    )
    assert dec.round_m([1]) == 1
    assert dec.round_m([2, 1]) == 2
    assert dec.round_m([3]) == 4       # pow2 bucket
    assert dec.round_m([4, 2]) == 4    # clamped at --spec-branches
    # tree off: the branch axis is pinned at 1 whatever the slots say
    lin = SpecDecoder(
        cfg, EngineConfig(speculative="ngram", num_speculative_tokens=4),
    )
    assert lin.round_m([1]) == 1
    assert lin.m_for(0) == 1


def test_round_k_buckets_to_pow2_clamped_at_cli_k():
    cfg = ModelConfig.tiny(dtype="float32")
    dec = SpecDecoder(
        cfg, EngineConfig(speculative="ngram", num_speculative_tokens=6),
    )
    assert dec.round_k([1]) == 1
    assert dec.round_k([2, 1]) == 2
    assert dec.round_k([3]) == 4       # pow2 bucket
    assert dec.round_k([5, 2]) == 6    # clamped to the CLI K
    # adaptive off: every slot runs the CLI K
    dec_off = SpecDecoder(
        cfg, EngineConfig(speculative="ngram", num_speculative_tokens=6,
                          spec_adaptive=False),
    )
    assert dec_off.k_for(0) == 6
    assert not dec_off.should_despec(0)


# ---------------------------------------------------------------------------
# Engine integration: greedy equality while K adapts mid-stream

@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    return cfg, llama.init_params(cfg, 0)


async def test_adaptive_greedy_differential_ngram(setup):
    """Mixed workload — one repetitive prompt (acceptance high, K grows)
    and one random prompt (acceptance collapses, K shrinks and the slot
    de-speculates) — stays token-identical to the baseline while the
    controller provably adjusts K both ways."""
    prompts = _prompts()  # [repetitive, random]
    ref, _, ref_hashes = await run_engine(setup, prompts)
    spec, st, hashes = await run_engine(
        setup, prompts, speculative="ngram", num_speculative_tokens=4,
        spec_adaptive=True, spec_min_k=1,
    )
    for (rt, _), (stk, _) in zip(ref, spec):
        assert rt == stk, "adaptive-K speculative output diverged"
    assert st["spec_adaptive"] is True
    assert st["spec_k_shrink_total"] > 0, "random prompt never shrank K"
    assert hashes == ref_hashes


async def test_adaptive_despec_on_collapsed_acceptance(setup):
    """A slot whose acceptance collapses is handed back to the fused
    round mid-stream (not at the context limit) and the continuation
    stays token-identical."""
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 256, 20).tolist()]  # nothing to look up
    ref, _, _ = await run_engine(setup, prompts, max_tokens=40)
    spec, st, _ = await run_engine(
        setup, prompts, max_tokens=40,
        speculative="ngram", num_speculative_tokens=4,
        spec_adaptive=True,
    )
    assert ref[0][0] == spec[0][0]
    assert st["spec_despec_total"] >= 1
    # despec fired from acceptance collapse: the run was nowhere near
    # the region limit (max_pages_per_seq=8 * 16 = 128 >> 20 + 40)


async def test_adaptive_differential_draft_batched(setup):
    """Batched cross-slot drafting (draft == target) is token-identical
    to both the baseline and the legacy per-slot drafting path."""
    prompts = _prompts()
    ref, _, ref_hashes = await run_engine(setup, prompts)
    batched, bst, bh = await run_engine(
        setup, prompts, draft=True, speculative="draft",
        num_speculative_tokens=4, spec_batch_draft=True,
    )
    perslot, pst, ph = await run_engine(
        setup, prompts, draft=True, speculative="draft",
        num_speculative_tokens=4, spec_batch_draft=False,
    )
    for (rt, _), (bt, _), (pt, _) in zip(ref, batched, perslot):
        assert rt == bt, "batched drafting diverged from baseline"
        assert rt == pt, "per-slot drafting diverged from baseline"
    assert bst["spec_acceptance_rate"] > 0.8
    assert bh == ref_hashes and ph == ref_hashes


async def test_batched_drafting_is_one_dispatch_per_round(setup):
    """The tentpole claim at engine level: N speculating slots draft in
    ONE device program per verify round (the per-slot path issued ~N*K).
    profile_round --spec reports the same counters standalone."""
    prompts = _prompts()
    _, bst, _ = await run_engine(
        setup, prompts, draft=True, speculative="draft",
        num_speculative_tokens=4, spec_batch_draft=True,
    )
    assert bst["spec_verify_dispatch_total"] > 0
    assert (bst["spec_draft_dispatch_total"]
            == bst["spec_verify_dispatch_total"])
    _, pst, _ = await run_engine(
        setup, prompts, draft=True, speculative="draft",
        num_speculative_tokens=4, spec_batch_draft=False,
    )
    # legacy: >= K dispatches per verify round once both slots speculate
    assert (pst["spec_draft_dispatch_total"]
            > pst["spec_verify_dispatch_total"])


async def test_mixed_spec_and_fused_rounds_stay_token_identical(setup):
    """A speculating slot co-resident with fused-round slots must not be
    advanced by the round's (garbage) column for its parked lane — the
    eligible request's output must equal its solo reference. Pins the
    dispatch-snapshot filter in _dispatch_round."""
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    rng = np.random.RandomState(9)
    elig_prompt = (rng.randint(1, 256, 6).tolist() * 4)
    pen_prompt = rng.randint(1, 256, 12).tolist()
    ref, _, _ = await run_engine(setup, [elig_prompt], max_tokens=24,
                                 speculative="ngram",
                                 num_speculative_tokens=4)
    eng = make_engine(setup, speculative="ngram", num_speculative_tokens=4)
    eng.start()
    try:
        async def one(req):
            toks = []
            async for out in eng.generate(req):
                toks.extend(out.token_ids)
            return toks

        pen = PreprocessedRequest(
            token_ids=pen_prompt,
            stop_conditions=StopConditions(max_tokens=24, ignore_eos=True),
        )
        pen.sampling_options = SamplingOptions(repetition_penalty=1.3)
        elig = PreprocessedRequest(
            token_ids=list(elig_prompt),
            stop_conditions=StopConditions(max_tokens=24, ignore_eos=True),
        )
        got = await asyncio.gather(one(pen), one(elig))
        assert eng.step_count > 0          # fused rounds really ran
        assert eng.spec.verify_steps > 0   # speculation really ran
        assert got[1] == ref[0][0], \
            "spec slot was corrupted by a co-resident fused round"
    finally:
        await eng.stop()


async def test_spec_effective_k_exported(setup):
    """The planner-facing gauge flows engine.metrics() -> WorkerStats ->
    exporter/system-server text."""
    eng = make_engine(setup, draft=True, speculative="draft",
                      num_speculative_tokens=4)
    eng.start()
    try:
        from tests.test_spec import drive

        await drive(eng, _prompts()[:1], max_tokens=16)
        m = eng.metrics()
        # draft == target: acceptance 1.0, so the slot's K never moved
        # off the cap (4) — and the slot may or may not be released yet
        # when metrics() snapshots (0 after release)
        assert m.worker_stats.spec_effective_k in (0.0, 4.0)
        assert eng.spec.effective_k_mean([0]) == 4.0
        assert eng.spec.effective_k_mean([]) == 0.0
    finally:
        await eng.stop()
    from dynamo_tpu.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.system_server import SystemServer

    exp = MetricsExporter(kv=None)
    exp.aggregator.update(m)
    assert "dynamo_spec_effective_k" in exp.render()

    class _Stub:
        def metrics(self):
            return m
    assert "dynamo_spec_effective_k" in SystemServer(_Stub()).render()


def test_worker_stats_effective_k_wire_compat():
    """Old payloads without the new field still deserialize."""
    m = ForwardPassMetrics.from_dict({
        "worker_id": "w0",
        "worker_stats": {"spec_proposed_total": 2},
        "kv_stats": {},
    })
    assert m.worker_stats.spec_effective_k == 0.0
    assert WorkerStats(spec_effective_k=2.5).spec_effective_k == 2.5


# ---------------------------------------------------------------------------
# Satellite fixes

def test_load_ctx_pages_clamps_padding_overflow():
    """A pow2-padded page list whose span exceeds the ctx region loads
    the region-sized prefix instead of raising the trace-time
    dynamic_update_slice error that killed whole engine rounds
    (BENCH_r05: 46 pages padded to 64 vs a 52-page region)."""
    cfg = ModelConfig.tiny(dtype="float32")
    ps, n_pages, region_pages = 16, 8, 3
    cache = llama.init_cache(cfg, n_pages, ps, jnp.float32)
    marker = jnp.arange(n_pages, dtype=jnp.float32)[None, None, :, None, None]
    cache = {k: jnp.broadcast_to(marker, v.shape).astype(v.dtype)
             for k, v in cache.items()}
    ctx = llama.init_ctx(cfg, 2, region_pages * ps, jnp.float32)
    # 3 real pages + pow2 padding to 4: span 4*16=64 > region 48
    out = llama.load_ctx_pages(
        ctx, cache, jnp.int32(0), jnp.asarray([5, 6, 7, 0], jnp.int32)
    )
    got = np.asarray(out["k"])[:, :, 0]           # lane 0: [L, kvh, S, hd]
    for b, page in enumerate((5, 6, 7)):
        assert np.all(got[:, :, b * ps:(b + 1) * ps] == float(page))


def test_emit_to_closed_loop_does_not_raise():
    """_fail_all during shutdown used to mask the root-cause exception
    with 'RuntimeError: Event loop is closed' raised from emit."""
    from dynamo_tpu.engine.engine import _Request
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )
    from dynamo_tpu.tokens import TokenBlockSequence

    loop = asyncio.new_event_loop()
    try:
        out: asyncio.Queue = asyncio.Queue()
    finally:
        loop.close()
    r = _Request(
        req=PreprocessedRequest(
            token_ids=[1, 2], stop_conditions=StopConditions(max_tokens=1),
        ),
        seq=TokenBlockSequence.from_tokens([1, 2], PS),
        out=out, loop=loop, tokens=[1, 2],
    )
    r.emit(RuntimeError("engine failure"))  # must not raise
