"""Wire-compatibility: the native C++ dcp-server must behave identically to
the Python store for the same client (runtime/client.py).

Builds the binary on demand (skips if no toolchain) and re-runs the client
suite's core scenarios against it: kv/watch/pubsub, lease keep-alive +
crash expiry, and component discovery + failover.
"""
import asyncio
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.component import DistributedRuntime

NATIVE = Path(__file__).resolve().parent.parent / "dynamo_tpu" / "native"
BINARY = NATIVE / "build" / "dcp-server"


@pytest.fixture(scope="module")
def dcp_binary():
    # always run make (incremental) so a stale binary never masks source
    # changes; the binary itself is gitignored
    if shutil.which("make") is None or shutil.which("g++") is None:
        if BINARY.exists():
            return BINARY
        pytest.skip("no native toolchain")
    r = subprocess.run(
        ["make", "-C", str(NATIVE)], capture_output=True, text=True
    )
    if r.returncode != 0:
        pytest.skip(f"native build failed: {r.stderr[-500:]}")
    return BINARY


@pytest.fixture
def dcp_server(dcp_binary):
    proc = subprocess.Popen(
        [str(dcp_binary), "0"], stdout=subprocess.PIPE, text=True
    )
    line = proc.stdout.readline()
    port = int(line.rsplit(":", 1)[-1])
    yield port
    proc.kill()
    proc.wait()


async def test_native_kv_watch_pubsub(dcp_server):
    c = await KvClient(port=dcp_server).connect()
    await c.put("m/a", "1")
    assert await c.get("m/a") == "1"
    assert await c.get("m/missing") is None
    # values with JSON + unicode content survive the C++ JSON round-trip
    payload = '{"host": "127.0.0.1", "port": 123, "name": "modèle-λ"}'
    await c.put("m/json", payload)
    assert await c.get("m/json") == payload

    w = await c.watch_prefix("m/")
    assert [k for k, _, _ in w.initial] == ["m/a", "m/json"]
    await c.put("m/b", "2")
    ev = await asyncio.wait_for(w.__anext__(), 2)
    assert (ev["event"], ev["key"], ev["value"]) == ("put", "m/b", "2")
    await c.delete("m/b")
    ev = await asyncio.wait_for(w.__anext__(), 2)
    assert ev["event"] == "delete"

    sub = await c.subscribe("events.>")
    c2 = await KvClient(port=dcp_server).connect()
    n = await c2.publish("events.x", "hello")
    assert n == 1
    ev = await asyncio.wait_for(sub.__anext__(), 2)
    assert ev["value"] == "hello" and ev["topic"] == "events.x"
    assert await c.get_prefix("m/") == [
        ("m/a", "1", 0), ("m/json", payload, 0)
    ]
    await c.close()
    await c2.close()


async def test_native_lease_expiry(dcp_server):
    c = await KvClient(port=dcp_server).connect()
    lease = await c.lease_grant(0.3)
    await c.put("inst/1", "up", lease=lease.id)
    await asyncio.sleep(1.0)  # keep-alive holds it
    assert await c.get("inst/1") == "up"
    lease._task.cancel()  # crash
    for _ in range(100):
        await asyncio.sleep(0.05)
        if await c.get("inst/1") is None:
            break
    assert await c.get("inst/1") is None
    await c.close()


async def test_native_component_failover(dcp_server):
    rt = await DistributedRuntime.connect(port=dcp_server)
    ep = rt.namespace("n").component("w").endpoint("generate")

    def mk(tag):
        async def handler(payload):
            yield {"from": tag}
        return handler

    w0 = await ep.serve(mk("w0"), worker_id="w0", lease_ttl_s=0.3)
    w1 = await ep.serve(mk("w1"), worker_id="w1", lease_ttl_s=0.3)
    cl = await rt.namespace("n").component("w").endpoint("generate").client()
    await cl.wait_for_instances(2)

    seen = set()
    for _ in range(4):
        async for m in cl.generate({}):
            seen.add(m["from"])
    assert seen == {"w0", "w1"}

    await w0.shutdown()
    t0 = asyncio.get_running_loop().time()
    while len(cl.instances) > 1:
        assert asyncio.get_running_loop().time() - t0 < 5
        await asyncio.sleep(0.02)
    async for m in cl.generate({}):
        assert m["from"] == "w1"

    await cl.stop()
    await w1.shutdown()
    await rt.close()


async def test_native_queue_longpoll(dcp_server):
    """The C++ server's queue plane must match the Python store's wire
    behavior: FIFO, cross-connection durability, parked long-poll, timeout."""
    producer = await KvClient(port=dcp_server).connect()
    consumer = await KvClient(port=dcp_server).connect()

    await producer.qpush("prefill", "j1")
    await producer.qpush("prefill", "j2")
    assert await producer.qlen("prefill") == 2
    assert await consumer.qpop("prefill") == "j1"
    assert await consumer.qpop("prefill") == "j2"
    assert await consumer.qpop("prefill") is None

    pop_task = asyncio.create_task(consumer.qpop("q2", timeout_s=5.0))
    await asyncio.sleep(0.1)
    await producer.qpush("q2", "late")
    assert await asyncio.wait_for(pop_task, 2) == "late"

    assert await consumer.qpop("empty", timeout_s=0.3) is None

    await producer.close()
    await consumer.close()
