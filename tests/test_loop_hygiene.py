"""Event-loop hygiene smoke test (slow): drive a representative
control-plane workload with asyncio debug mode on and fail if any
callback holds the loop for more than 100 ms.

Asyncio's debug mode logs "Executing <Handle ...> took X seconds" on the
``asyncio`` logger for every callback slower than
``loop.slow_callback_duration`` — exactly the class of regression DTL002
catches statically (a ``time.sleep``/blocking read smuggled into an
async path) but measured, so it also catches blocking work the linter
cannot see (C extensions, accidental O(n^2) handlers).
"""
import asyncio
import logging

import pytest

from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.store import serve_store


class _SlowCallbackCatcher(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.slow: list[str] = []

    def emit(self, record):
        msg = record.getMessage()
        if "Executing" in msg and "took" in msg:
            self.slow.append(msg)


@pytest.mark.slow
async def test_control_plane_has_no_slow_loop_callbacks():
    base = set(asyncio.all_tasks())  # harness wrapper tasks are not leaks
    loop = asyncio.get_running_loop()
    catcher = _SlowCallbackCatcher()
    alog = logging.getLogger("asyncio")
    alog.addHandler(catcher)
    prev_level = alog.level
    alog.setLevel(logging.WARNING)
    loop.set_debug(True)
    loop.slow_callback_duration = 0.1
    try:
        server, store = await serve_store(port=0, sweep_interval_s=0.05)
        port = server.sockets[0].getsockname()[1]
        clients = [await KvClient(port=port).connect() for _ in range(4)]
        try:
            for round_ in range(25):
                for i, c in enumerate(clients):
                    await c.put(f"k/{i}/{round_}", "v" * 256)
                    assert await c.get(f"k/{i}/{round_}") == "v" * 256
                    await c.qpush(f"q/{i}", f"round-{round_}")
                await asyncio.sleep(0)
        finally:
            for c in clients:
                await c.close()
            server.close()
            await server.wait_closed()
        # give debug-mode bookkeeping a tick to flush its warnings
        await asyncio.sleep(0.05)
        # task hygiene: closing the server must cancel its sweeper, and
        # closing a client must tear down its rx task — anything left is
        # a leak that accumulates one 0.5s-cadence task per store in the
        # suite's shared loop
        leftover = [
            t for t in asyncio.all_tasks()
            if t not in base and t is not asyncio.current_task()
            and not t.done()
        ]
        assert not leftover, f"stray tasks after close: {leftover}"
    finally:
        loop.set_debug(False)
        alog.removeHandler(catcher)
        alog.setLevel(prev_level)
    assert not catcher.slow, (
        "event-loop callbacks exceeded 100 ms:\n" + "\n".join(catcher.slow))
