"""HTTP frontend e2e tests (reference lib/llm/tests/http-service.rs:472).

Drives the full chain — HTTP -> preprocessor -> engine -> backend -> SSE —
against the echo engine (deterministic) and the real TpuEngine on the tiny
CPU model.
"""
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_tpu.backend import Backend
from dynamo_tpu.engines import EchoEngine
from dynamo_tpu.frontend import HttpService, ModelChain, ModelManager
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.tokenizer import make_test_tokenizer

WORDS = [f"w{i}" for i in range(50)] + ["hello", "world", "STOP"]


def make_echo_service() -> HttpService:
    tok = make_test_tokenizer(WORDS)
    fmt = PromptFormatter(
        template="{% for m in messages %}{{ m.content }} {% endfor %}"
    )
    chain = ModelChain(
        name="echo",
        preprocessor=OpenAIPreprocessor(tokenizer=tok, formatter=fmt, model_name="echo"),
        engine=EchoEngine(delay_s=0.0),
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    return HttpService(manager)


async def with_client(svc):
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    return client


async def sse_events(resp):
    dec = SseDecoder()
    events = []
    async for chunk in resp.content.iter_any():
        events.extend(dec.feed(chunk))
    return events


async def test_models_endpoint():
    client = await with_client(make_echo_service())
    r = await client.get("/v1/models")
    assert r.status == 200
    body = await r.json()
    assert [m["id"] for m in body["data"]] == ["echo"]
    await client.close()


async def test_chat_completion_unary():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 2,
        },
    )
    assert r.status == 200
    body = await r.json()
    assert body["object"] == "chat.completion"
    # echo engine returns the prompt tokens back: "hello world"
    assert body["choices"][0]["message"]["content"].strip() == "hello world"
    assert body["choices"][0]["finish_reason"] == "length"
    assert body["usage"]["completion_tokens"] == 2
    await client.close()


async def test_chat_completion_streaming():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 4,
            "stream": True,
            "stream_options": {"include_usage": True},
        },
    )
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    events = await sse_events(r)
    assert events[-1].is_done
    chunks = [e.json() for e in events[:-1]]
    text = "".join(
        c["choices"][0]["delta"].get("content", "")
        for c in chunks
        if c.get("choices")
    )
    assert text.strip() == "hello world hello world"
    finishes = [
        c["choices"][0]["finish_reason"] for c in chunks if c.get("choices")
    ]
    assert finishes[-1] == "length"
    usage = [c["usage"] for c in chunks if c.get("usage")]
    assert usage and usage[0]["completion_tokens"] == 4
    await client.close()


async def test_completions_endpoint():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/completions",
        json={"model": "echo", "prompt": "hello world", "max_tokens": 2},
    )
    assert r.status == 200
    body = await r.json()
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"].strip() == "hello world"
    await client.close()


async def test_stop_strings_enforced():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello STOP world"}],
            "max_tokens": 8,
            "stop": ["STOP"],
        },
    )
    body = await r.json()
    # echo replays "hello STOP world ..." -> cut before STOP
    assert body["choices"][0]["message"]["content"].strip() == "hello"
    assert body["choices"][0]["finish_reason"] == "stop"
    await client.close()


async def test_unknown_model_404_and_bad_request_400():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={"model": "nope", "messages": [{"role": "user", "content": "x"}]},
    )
    assert r.status == 404
    r = await client.post(
        "/v1/chat/completions", json={"model": "echo", "messages": []}
    )
    assert r.status == 400
    r = await client.post("/v1/chat/completions", data=b"{not json")
    assert r.status == 400
    await client.close()


async def test_n_choices():
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 1,
            "n": 2,
        },
    )
    body = await r.json()
    assert [c["index"] for c in body["choices"]] == [0, 1]
    await client.close()


async def test_metrics_and_health():
    svc = make_echo_service()
    client = await with_client(svc)
    await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 1,
        },
    )
    r = await client.get("/metrics")
    text = await r.text()
    assert 'dynamo_http_service_requests_total{' in text
    assert 'model="echo"' in text
    r = await client.get("/health")
    body = await r.json()
    assert body["status"] == "healthy" and body["models"] == ["echo"]
    await client.close()


# ---------------------------------------------------------------------------
# e2e against the real engine on the tiny CPU model


@pytest.fixture(scope="module")
def tpu_service():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig

    # vocab larger than test tokenizer's so all token ids are valid
    cfg = ModelConfig.tiny(dtype="float32")
    ecfg = EngineConfig(
        num_pages=64, page_size=16, max_pages_per_seq=8,
        max_decode_slots=4, prefill_buckets=(32, 64), cache_dtype="float32",
    )
    engine = TpuEngine(cfg, ecfg, mesh_config=MeshConfig(tp=1))
    tok = make_test_tokenizer(WORDS)
    chain = ModelChain(
        name="tiny",
        preprocessor=OpenAIPreprocessor(tokenizer=tok, model_name="tiny"),
        engine=engine,
        backend=Backend(tok),
    )
    manager = ModelManager()
    manager.register(chain)
    # the manager/engine are loop-independent; each test builds a fresh
    # HttpService (aiohttp Applications bind to one event loop)
    yield manager


async def test_tpu_engine_chat_stream_e2e(tpu_service):
    client = await with_client(HttpService(tpu_service))
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello world w1 w2"}],
            "max_tokens": 6,
            "stream": True,
        },
    )
    assert r.status == 200
    events = await sse_events(r)
    assert events[-1].is_done
    chunks = [e.json() for e in events[:-1]]
    finishes = [
        c["choices"][0]["finish_reason"] for c in chunks if c.get("choices")
    ]
    assert finishes[-1] in ("stop", "length")
    await client.close()


async def test_tpu_engine_unary_deterministic(tpu_service):
    client = await with_client(HttpService(tpu_service))
    body = {
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello world"}],
        "max_tokens": 5,
    }
    r1 = await (await client.post("/v1/chat/completions", json=body)).json()
    r2 = await (await client.post("/v1/chat/completions", json=body)).json()
    assert r1["choices"][0]["message"]["content"] == r2["choices"][0]["message"]["content"]
    await client.close()


async def test_service_keeps_empty_manager():
    """Regression: an EMPTY ModelManager is falsy (len 0); HttpService must
    keep it rather than replacing it with a private clone — dynamic
    discovery registers models into the original AFTER service start."""
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.frontend.service import HttpService

    manager = ModelManager()
    svc = HttpService(manager)          # constructed while still empty
    assert svc.manager is manager
    client = TestClient(TestServer(svc.app))
    await client.start_server()
    r = await client.get("/v1/models")
    assert (await r.json())["data"] == []
    # late discovery: register into the ORIGINAL manager; service must see it
    tok = make_test_tokenizer(WORDS)
    manager.register(ModelChain(
        name="echo",
        preprocessor=OpenAIPreprocessor(
            tokenizer=tok, formatter=PromptFormatter(), model_name="echo"
        ),
        engine=EchoEngine(delay_s=0.0),
        backend=Backend(tok),
    ))
    r = await client.get("/v1/models")
    assert [m["id"] for m in (await r.json())["data"]] == ["echo"]
    await client.close()


async def test_llm_metrics_annotation_stream():
    """In-band per-request metrics (reference ANNOTATION_LLM_METRICS):
    opting in via nvext annotations appends a metrics event to the SSE
    stream before [DONE]."""
    client = await with_client(make_echo_service())
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "echo",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 2,
            "stream": True,
            "nvext": {"annotations": ["llm_metrics"]},
        },
    )
    assert r.status == 200
    events = await sse_events(r)
    metric_events = [e.json() for e in events
                     if not e.is_done and "nvext" in e.data]
    assert len(metric_events) == 1
    m = metric_events[0]["nvext"]["metrics"]
    assert m["completion_tokens"] == 2
    assert m["prompt_tokens"] > 0
    assert m["ttft_s"] is not None and m["ttft_s"] >= 0
    # without the annotation: no metrics event
    r2 = await client.post(
        "/v1/chat/completions",
        json={"model": "echo",
              "messages": [{"role": "user", "content": "hello"}],
              "max_tokens": 2, "stream": True},
    )
    events2 = await sse_events(r2)
    assert not [e for e in events2 if not e.is_done and "nvext" in e.data]
    await client.close()
