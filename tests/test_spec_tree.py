"""Tree speculative decoding (dynamo_tpu/spec/ --spec-tree).

The keystone stays differential: greedy tree speculation — n-gram trie
and comb draft proposers, across (K, branches) shapes — must produce
token-for-token identical output to both the linear-chain speculative
engine and the non-speculative baseline, and must leave the prefix-cache
block-hash registry identical after sibling-row rollbacks (the verify
scores sibling nodes that alias the SAME ctx positions; only the
accepted path's KV rows are ever committed).

On top of that: the packed-tree metadata walk (tree_meta), the trie /
comb proposers, the penalized acceptance walk's PRNG-stream
compatibility with the unpenalized walk, and the acceptance gate's
despec -> fused-round -> re-arm cycle under a synthetic low-acceptance
stream.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.protocols.common import SamplingOptions
from dynamo_tpu.spec.proposer import NGramProposer, comb_parents
from dynamo_tpu.spec.verifier import (
    accept_tree,
    accept_tree_penalized,
    tree_meta,
)
from tests.test_spec import _prompts, make_engine, run_engine


# ---------------------------------------------------------------------------
# tree_meta (the on-device pointer walk)

def test_tree_meta_depth_ancestors_padding():
    #        0(root)  1<-0  2<-0  3<-1  4=pad
    parents = jnp.asarray([-1, 0, 0, 1, -2], jnp.int32)
    depth, anc, valid = tree_meta(parents)
    assert np.asarray(depth).tolist() == [0, 1, 1, 2, -1]
    assert np.asarray(valid).tolist() == [True, True, True, True, False]
    anc = np.asarray(anc)
    # ancestor-or-self rows ARE the in-chunk visibility mask
    assert anc[0].tolist() == [True, False, False, False, False]
    assert anc[3].tolist() == [True, True, False, True, False]
    assert anc[2].tolist() == [True, False, True, False, False]
    # padding row is fully masked — the scorer emits zeros for it
    assert anc[4].tolist() == [False] * 5


def test_tree_meta_linear_chain_reduces_to_causal():
    parents = jnp.asarray([-1, 0, 1, 2], jnp.int32)
    depth, anc, valid = tree_meta(parents)
    assert np.asarray(depth).tolist() == [0, 1, 2, 3]
    # lower-triangular == plain causal: the linear chain is the
    # degenerate tree
    assert np.array_equal(np.asarray(anc), np.tri(4, dtype=bool))


# ---------------------------------------------------------------------------
# proposers

def test_comb_parents_shape():
    # depth 2, fan 3: root, 3 children of root, 3 children of the
    # level-0 spine (node 1)
    assert comb_parents(2, 3) == [-1, 0, 0, 0, 1, 1, 1]
    # m=1 degenerates to the linear chain
    assert comb_parents(3, 1) == [-1, 0, 1, 2]


def test_ngram_propose_tree_merges_shared_prefixes():
    p = NGramProposer(k=4, max_n=2, min_n=1)
    # tail [1, 2] continues with [4, ...] (recent) and [3, ...] (older)
    history = [1, 2, 3, 9, 1, 2, 4, 9, 1, 2]
    toks, pars = p.propose_tree(history, depth=2, branches=2, budget=16)
    assert len(toks) == len(pars) <= 15
    # both continuations fork at the root (parent 0 = pending token)
    assert pars.count(0) == 2
    first_level = [t for t, par in zip(toks, pars) if par == 0]
    assert set(first_level) == {3, 4}
    # parents always point at earlier nodes (packable as-is)
    for i, par in enumerate(pars):
        assert 0 <= par <= i


def test_ngram_propose_tree_budget_cap_and_fallback():
    p = NGramProposer(k=4, max_n=3, min_n=1)
    history = [1, 2, 3, 9, 1, 2, 4, 9, 1, 2]
    toks, pars = p.propose_tree(history, depth=4, branches=4, budget=4)
    assert len(toks) <= 3  # budget - 1: the root takes a slot
    # no match at all -> the linear path's zero chain
    toks, pars = p.propose_tree([1, 2, 3, 4], depth=3, branches=2,
                                budget=8)
    assert toks == [0, 0, 0]
    assert pars == [0, 1, 2]


# ---------------------------------------------------------------------------
# penalized acceptance: PRNG-stream compatibility

def test_penalized_walk_matches_unpenalized_at_zero_penalties():
    """accept_tree_penalized with a zero histogram and identity
    penalties must draw the SAME PRNG stream and produce bit-identical
    (tokens, path, count, key) as accept_tree — the contract that lets
    the engine mix penalized and plain rows in one verify program."""
    rng = np.random.RandomState(11)
    V, T, D = 32, 7, 3
    parents = jnp.asarray([-1, 0, 0, 1, 1, 3, -2], jnp.int32)
    _, _, valid = tree_meta(parents)
    toks = jnp.asarray(rng.randint(1, V, T), jnp.int32)
    logits = jnp.asarray(rng.randn(T, V).astype(np.float32) * 3)
    for temp, tk, tp in ((0.0, 0, 1.0), (0.9, 8, 0.95), (1.3, 0, 1.0)):
        key = jnp.asarray([5, 17], jnp.uint32)
        a = accept_tree(
            logits, toks, parents, valid, key, jnp.float32(temp),
            jnp.int32(tk), jnp.float32(tp), max_top_k=8, d_max=D,
        )
        b = accept_tree_penalized(
            logits, toks, parents, valid, key, jnp.float32(temp),
            jnp.int32(tk), jnp.float32(tp),
            jnp.zeros(V, jnp.int32), jnp.float32(0.0), jnp.float32(0.0),
            jnp.float32(1.0), max_top_k=8, d_max=D,
        )
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y)), (
                f"penalized walk diverged at temp={temp}"
            )


# ---------------------------------------------------------------------------
# engine differentials

@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig.tiny(dtype="float32")
    return cfg, llama.init_params(cfg, 0)


# non-speculative reference runs, computed once per (max_tokens) and
# reused across the differential tests below (each engine run costs
# ~10s of JIT on CPU; the 120s per-test budget can't fit a full sweep)
_REF: dict = {}


async def _baseline(setup, max_tokens=24):
    if max_tokens not in _REF:
        _REF[max_tokens] = await run_engine(
            setup, _prompts(), max_tokens=max_tokens
        )
    return _REF[max_tokens]


@pytest.mark.parametrize("k", (2, 4, 8))
async def test_tree_greedy_differential_ngram(setup, k):
    """THE pin: greedy tree speculation is token-identical to the
    linear-chain speculative engine AND the plain baseline across
    K x branches shapes, and the prefix-cache hash registry matches a
    clean run despite sibling-row rollbacks."""
    prompts = _prompts()
    ref, _, ref_hashes = await _baseline(setup)
    lin, _, lin_hashes = await run_engine(
        setup, prompts, speculative="ngram",
        num_speculative_tokens=k,
    )
    assert lin_hashes == ref_hashes
    for b in (2, 4):
        tree, st, hashes = await run_engine(
            setup, prompts, speculative="ngram",
            num_speculative_tokens=k, spec_tree=True,
            spec_branches=b,
        )
        for (rt, _), (lt, _), (tt, _) in zip(ref, lin, tree):
            assert rt == tt, f"K={k} B={b}: tree != baseline"
            assert lt == tt, f"K={k} B={b}: tree != linear"
        assert st["spec_tree_verify_steps"] > 0
        # KV-hash consistency after sibling-row rollback: only the
        # accepted path was committed, blocks sealed under the same
        # chained hashes as a clean run
        assert hashes == ref_hashes, f"K={k} B={b}"


async def test_tree_greedy_differential_comb_draft(setup):
    """Comb drafts (batch_draft branch mode) stay token-identical with
    draft == target, and acceptance is near-total — the multi-branch
    draft program feeds the verify without a host round trip."""
    prompts = _prompts()
    ref, _, ref_hashes = await _baseline(setup)
    tree, st, hashes = await run_engine(
        setup, prompts, draft=True, speculative="draft",
        num_speculative_tokens=4, spec_tree=True, spec_branches=2,
    )
    for (rt, _), (tt, _) in zip(ref, tree):
        assert rt == tt, "comb-draft tree diverged from baseline"
    assert st["spec_acceptance_rate"] > 0.9
    assert st["spec_tree_verify_steps"] > 0
    assert hashes == ref_hashes


async def test_tree_seeded_temperature_reproducible(setup):
    so = SamplingOptions(temperature=0.8, top_k=8, seed=7)
    prompts = _prompts()
    runs = []
    for _ in range(2):
        res, _, _ = await run_engine(
            setup, prompts, so=so, speculative="ngram",
            num_speculative_tokens=4, spec_tree=True, spec_branches=2,
        )
        runs.append([t for t, _ in res])
    assert runs[0] == runs[1]


async def test_tree_penalized_greedy_differential(setup):
    """Penalized greedy requests ride the penalized tree walk (counts
    advancing down the accepted path) and still match the fused
    baseline token-for-token."""
    so = SamplingOptions(frequency_penalty=0.6, presence_penalty=0.3,
                        repetition_penalty=1.2)
    prompts = _prompts()
    ref, _, _ = await run_engine(setup, prompts, so=so)
    tree, st, _ = await run_engine(
        setup, prompts, so=so, speculative="ngram",
        num_speculative_tokens=4, spec_tree=True, spec_branches=2,
    )
    for (rt, _), (tt, _) in zip(ref, tree):
        assert rt == tt, "penalized tree diverged"
    assert st["spec_tree_verify_steps"] > 0


async def test_gate_despec_and_rearm_cycle(setup):
    """Synthetic low-acceptance stream (random prompts reject n-gram
    drafts): the acceptance gate must hand streams back to the fused
    round, re-arm them after the re-arm budget, and the whole gated
    run stays token-identical to the plain baseline."""
    prompts = _prompts()
    ref, _, ref_hashes = await _baseline(setup, max_tokens=48)
    gated, st, hashes = await run_engine(
        setup, prompts, max_tokens=48, speculative="ngram",
        num_speculative_tokens=4, spec_tree=True, spec_branches=2,
        spec_adaptive=False,
        spec_gate_acceptance=0.5, spec_gate_window=2,
        spec_rearm_tokens=4,
    )
    for (rt, _), (gt, _) in zip(ref, gated):
        assert rt == gt, "gated run diverged from baseline"
    assert st["spec_gated_despec_total"] >= 1
    assert st["spec_rearm_total"] >= 1
    assert hashes == ref_hashes


async def test_gate_without_rearm_stays_despeculated(setup):
    """spec_rearm_tokens=0 makes the gate permanent: streams gate once
    and finish on the fused round, never re-arming."""
    prompts = _prompts()
    gated, st, _ = await run_engine(
        setup, prompts, max_tokens=32, speculative="ngram",
        num_speculative_tokens=4, spec_tree=True, spec_branches=2,
        spec_adaptive=False,
        spec_gate_acceptance=0.9, spec_gate_window=1,
        spec_rearm_tokens=0,
    )
    assert st["spec_gated_despec_total"] >= 1
    assert st["spec_rearm_total"] == 0


async def test_tree_metrics_surface(setup):
    """Tree counters reach SpecDecoder.stats(), the engine WorkerStats
    distribution fields, and the SPEC scrape registry."""
    from dynamo_tpu.spec.metrics import SPEC

    prompts = _prompts()
    eng = make_engine(
        setup, speculative="ngram", num_speculative_tokens=4,
        spec_tree=True, spec_branches=2,
    )
    eng.start()
    try:
        from tests.test_spec import drive

        nodes0 = SPEC.get("dynamo_spec_tree_nodes_total")
        await drive(eng, prompts, max_tokens=16)
        st = eng.spec.stats()
        assert st["spec_tree"] is True
        assert st["spec_tree_nodes_total"] > 0
        assert st["spec_tree_mean_path_len"] >= 0.0
        assert len(st["spec_branch_accept_hist"]) == 2
        m = eng.metrics()
        ws = m.worker_stats
        assert ws.spec_tree_nodes_total == st["spec_tree_nodes_total"]
        assert ws.spec_effective_k_p95 >= ws.spec_effective_k_p50 >= 0.0
        # the scrape registry advanced and renders all four families
        assert SPEC.get("dynamo_spec_tree_nodes_total") > nodes0
        text = SPEC.render()
        for fam in ("dynamo_spec_tree_nodes_total",
                    "dynamo_spec_tree_accepted_path_len_total",
                    "dynamo_spec_tree_gated_despecs_total",
                    "dynamo_spec_accept_rate"):
            assert fam in text
    finally:
        await eng.stop()
