"""Cross-host single-engine test (BASELINE config 4; reference
flags.rs:86-101 MultiNodeConfig + leader_worker_barrier.rs): TWO OS
processes form one jax.distributed mesh (2 hosts x 2 virtual CPU devices,
tp=4); the leader runs the full engine scheduler and broadcasts every
dispatch over the store; the follower replays in lockstep. The served
tokens must equal a single-process engine on an identically-shaped mesh.
"""
import asyncio
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from dynamo_tpu.runtime.store import serve_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COMMON = """
import os, sys, json, asyncio
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
sys.path.insert(0, {repo!r})
jax.distributed.initialize(coordinator_address="127.0.0.1:{coord}",
                           num_processes=2, process_id={pid})
import numpy as np
from jax.sharding import Mesh
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.barrier import LeaderBarrier, WorkerBarrier

cfg = ModelConfig.tiny(dtype="float32", num_kv_heads=4, num_heads=8)
ecfg = EngineConfig(num_pages=32, page_size=16, max_pages_per_seq=8,
                    max_decode_slots=2, prefill_buckets=(32, 64),
                    cache_dtype="float32", flush_every=2,
                    max_inflight_rounds=1)
mesh = make_mesh(MeshConfig(tp=4), jax.devices())
params = llama.init_params(cfg, 0)
"""

LEADER = COMMON + """
from dynamo_tpu.engine.multihost import (
    CommandStream, make_dispatch_sink, stop_followers,
)
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions

async def main():
    kv = await KvClient(port={store}).connect()
    await LeaderBarrier(kv, "mh-e1", num_workers=1,
                        timeout_s=60).sync("up")
    stream = CommandStream(kv, asyncio.get_running_loop(),
                           "tt", "e1", "run1", n_followers=1)
    await stream.announce()
    eng = TpuEngine(cfg, ecfg, params=params, mesh=mesh,
                    on_dispatch=make_dispatch_sink(stream))
    outs = []
    for base in (1, 40):
        req = PreprocessedRequest(
            token_ids=list(range(base, base + 20)),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        outs.append(toks)
    await eng.stop()
    await stream.drain()  # batched frames must precede the stop command
    await stop_followers(kv, "tt", "e1", "run1", 1, stream.seq)
    print("RESULT " + json.dumps(outs), flush=True)
    await kv.close()

asyncio.run(main())
"""

FOLLOWER = COMMON + """
from dynamo_tpu.engine.multihost import Follower

async def main():
    kv = await KvClient(port={store}).connect()
    await WorkerBarrier(kv, "mh-e1", "h1", timeout_s=60).sync()
    eng = TpuEngine(cfg, ecfg, params=params, mesh=mesh)  # never started
    f = Follower(eng, kv, "tt", "e1", "run1", host_index=1)
    await f.run()
    print("FOLLOWER OK " + str(f.commands_applied), flush=True)
    await kv.close()

asyncio.run(main())
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.asyncio_timeout(420)
async def test_two_process_lockstep_engine():
    server, store = await serve_store(port=0, sweep_interval_s=0.05)
    store_port = server.sockets[0].getsockname()[1]
    coord = _free_port()

    def spawn(code, pid):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        return subprocess.Popen(
            [sys.executable, "-c",
             textwrap.dedent(code).format(
                 repo=REPO, coord=coord, pid=pid, store=store_port
             )],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )

    leader = spawn(LEADER, 0)
    follower = spawn(FOLLOWER, 1)
    try:
        l_out, l_err = await asyncio.to_thread(leader.communicate, None, 360)
        f_out, f_err = await asyncio.to_thread(follower.communicate, None, 60)
    except subprocess.TimeoutExpired:
        leader.kill()
        follower.kill()
        raise
    finally:
        server.close()
    assert leader.returncode == 0, f"leader failed:\n{l_err[-3000:]}"
    assert follower.returncode == 0, f"follower failed:\n{f_err[-3000:]}"
    assert "FOLLOWER OK" in f_out
    result_line = [ln for ln in l_out.splitlines()
                   if ln.startswith("RESULT ")][0]
    outs = json.loads(result_line[len("RESULT "):])
    assert all(len(o) == 6 for o in outs)

    # reference: identical mesh SHAPE in one process (same partitioning ->
    # same numerics), same params/seed
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama as llama_mod
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )
    import jax

    cfg = ModelConfig.tiny(dtype="float32", num_kv_heads=4, num_heads=8)
    ecfg = EngineConfig(num_pages=32, page_size=16, max_pages_per_seq=8,
                        max_decode_slots=2, prefill_buckets=(32, 64),
                        cache_dtype="float32", flush_every=2,
                        max_inflight_rounds=1)
    mesh = make_mesh(MeshConfig(tp=4), jax.devices()[:4])
    eng = TpuEngine(cfg, ecfg, params=llama_mod.init_params(cfg, 0),
                    mesh=mesh)
    expected = []
    for base in (1, 40):
        req = PreprocessedRequest(
            token_ids=list(range(base, base + 20)),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        expected.append(toks)
    await eng.stop()
    assert outs == expected, (
        "multihost lockstep engine must serve the same tokens as the "
        "single-process engine on an identically-sharded mesh"
    )
